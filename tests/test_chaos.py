"""Chaos suite: the deterministic fault-injection subsystem
(``photon_tpu/chaos``) and the elasticity it exists to prove.

SURVEY §5 "Failure detection / elastic recovery": the reference recovers
round-by-round (failed task re-queued, worker restarted, failure budget).
Here every failure mode is an injectable, seeded event: TCP envelope faults
(drop/delay/duplicate/corrupt, caught by CRC32 framing), object-store faults
(slow/partial/bit-flipped writes, caught by checkpoint checksums), and
SIGKILL-equivalent node crashes at chosen phases. The soak at the bottom
drives the whole loop through sustained randomized failures.

Run the full suite with a fixed seed via ``make chaos``; the fast tests are
tier-1 so injector plumbing can't rot.
"""

import random
import socket
import time

import numpy as np
import pytest

from photon_tpu import chaos
from photon_tpu.config.schema import ChaosConfig
from photon_tpu.federation.messages import Envelope, FitRes, Query
from tests.test_federation import make_app, make_cfg

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_injector():
    """Never leak a process-global injector into another test."""
    yield
    chaos.uninstall()


def _chaos_cfg(**kw) -> ChaosConfig:
    return ChaosConfig(enabled=True, seed=1234, **kw)


# ---------------------------------------------------------------------------
# injector unit tests (fast, tier-1 smoke)
# ---------------------------------------------------------------------------


def test_disabled_chaos_is_a_noop():
    assert chaos.active() is None
    assert chaos.install(ChaosConfig()) is None  # enabled=False clears
    assert chaos.install(None) is None
    chaos.crash_point("mid-fit", 1, "node0")  # must not raise or exit


def test_injector_schedule_is_deterministic():
    cfg = _chaos_cfg(tcp_drop_p=0.3, tcp_delay_p=0.3, tcp_duplicate_p=0.3,
                     tcp_corrupt_p=0.3)
    a = chaos.FaultInjector(cfg, scope="node0")
    b = chaos.FaultInjector(cfg, scope="node0")
    plans_a = [a.tcp_plan() for _ in range(64)]
    plans_b = [b.tcp_plan() for _ in range(64)]
    assert plans_a == plans_b
    assert a.counts == b.counts
    # a different scope draws a different stream
    c = chaos.FaultInjector(cfg, scope="node1")
    assert [c.tcp_plan() for _ in range(64)] != plans_a


def test_corrupt_bytes_flips_exactly_one_bit():
    inj = chaos.FaultInjector(_chaos_cfg(), scope="x")
    data = bytes(range(256))
    out = inj.corrupt_bytes(data)
    assert len(out) == len(data)
    diff = [(x ^ y) for x, y in zip(data, out) if x != y]
    assert len(diff) == 1 and bin(diff[0]).count("1") == 1


def test_crash_point_matching_and_marker(tmp_path):
    marker = tmp_path / "crashed"
    crashes = []
    cfg = _chaos_cfg(crash_phase="mid-fit", crash_round=2,
                     crash_node_id="node1", crash_marker=str(marker))
    chaos.install(cfg, scope="node1", crash_fn=crashes.append)
    chaos.crash_point("pre-fit", 2, "node1")  # wrong phase
    chaos.crash_point("mid-fit", 1, "node1")  # wrong round
    chaos.crash_point("mid-fit", 2, "node0")  # wrong node
    assert crashes == [] and not marker.exists()
    chaos.crash_point("mid-fit", 2, "node1")
    assert crashes == [137] and marker.exists()
    chaos.crash_point("mid-fit", 2, "node1")  # marker disarms the repeat
    assert crashes == [137]


# ---------------------------------------------------------------------------
# TCP envelope faults + CRC framing
# ---------------------------------------------------------------------------


def _pair():
    from photon_tpu.federation.tcp import SocketConn

    a, b = socket.socketpair()
    return SocketConn(a), SocketConn(b)


def test_tcp_corrupt_frame_detected_by_crc():
    from photon_tpu.federation.tcp import CorruptFrameError

    tx, rx = _pair()
    chaos.install(_chaos_cfg(tcp_corrupt_p=1.0), scope="t")
    tx.send(Envelope(Query("ping"), 1))
    with pytest.raises(CorruptFrameError):
        rx.recv()
    # CorruptFrameError IS an EOFError: every existing teardown path applies
    assert issubclass(CorruptFrameError, EOFError)
    tx.close(); rx.close()


def test_tcp_duplicate_and_drop():
    tx, rx = _pair()
    chaos.install(_chaos_cfg(tcp_duplicate_p=1.0), scope="t")
    tx.send(Envelope(Query("ping"), 7))
    first, second = rx.recv(), rx.recv()
    assert first.msg_id == second.msg_id == 7

    chaos.install(_chaos_cfg(tcp_drop_p=1.0), scope="t")
    tx.send(Envelope(Query("ping"), 8))
    rx.sock.settimeout(0.2)
    with pytest.raises(OSError):  # nothing ever arrives
        rx.recv()
    tx.close(); rx.close()


def test_tcp_chaos_exempts_non_envelopes():
    """HELLO/registration frames must never be faulted — membership control
    cannot be wedged by the injector."""
    tx, rx = _pair()
    chaos.install(_chaos_cfg(tcp_drop_p=1.0, tcp_corrupt_p=1.0), scope="t")
    tx.send({"kind": "__hello__", "node_id": "n0"})
    assert rx.recv()["node_id"] == "n0"
    tx.close(); rx.close()


def test_tcp_frames_unchanged_with_chaos_off():
    tx, rx = _pair()
    env = Envelope(Query("ping", {"k": 1}), 42)
    tx.send(env)
    got = rx.recv()
    assert got.msg_id == 42 and got.msg.action == "ping"
    tx.close(); rx.close()


# ---------------------------------------------------------------------------
# object-store faults
# ---------------------------------------------------------------------------


def test_store_bitflip_lands_corrupt_object(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    data = bytes(1000)
    chaos.install(_chaos_cfg(store_bitflip_p=1.0), scope="srv")
    s.put("obj.bin", data)
    got = s.get("obj.bin")
    assert len(got) == len(data) and got != data  # well-formed, wrong bytes


def test_store_partial_write_never_lands(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    chaos.install(_chaos_cfg(store_partial_p=1.0), scope="srv")
    s.put("obj.bin", b"x" * 100)
    assert not s.exists("obj.bin")
    assert s.list("") == []  # the leaked .tmp is not a listable object
    leaked = [p for p in tmp_path.rglob("*") if ".tmp-" in p.name]
    assert len(leaked) == 1  # the torn temp file is there for forensics


def test_store_slow_write_still_correct(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    inj = chaos.install(_chaos_cfg(store_slow_p=1.0, store_slow_max_s=0.01), scope="srv")
    s.put("obj.bin", b"payload")
    assert s.get("obj.bin") == b"payload"
    assert inj.counts["store_slow"] == 1


def test_store_roundtrip_identical_with_chaos_off(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    data = np.random.default_rng(0).integers(0, 256, 4096, dtype=np.uint8).tobytes()
    s.put("obj.bin", data)
    assert s.get("obj.bin") == data


# ---------------------------------------------------------------------------
# object-store READ faults (ISSUE 8 satellite): get/get_to_file honor the
# slow/partial/bitflip plan like put does — the object AT REST stays intact
# ---------------------------------------------------------------------------


def test_store_read_faults_honor_the_plan(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    data = np.random.default_rng(7).integers(0, 256, 4096, dtype=np.uint8).tobytes()
    s.put("obj.bin", data)  # written clean: chaos installs after

    inj = chaos.install(_chaos_cfg(store_bitflip_p=1.0), scope="srv")
    got = s.get("obj.bin")
    assert len(got) == len(data) and got != data  # well-formed, wrong bytes
    assert inj.counts["store_read_bitflip"] == 1
    chaos.uninstall()
    assert s.get("obj.bin") == data  # bad RAM on the read, not the disk

    inj = chaos.install(_chaos_cfg(store_partial_p=1.0), scope="srv")
    assert s.get("obj.bin") == data[: len(data) // 2]  # short read
    assert inj.counts["store_read_partial"] == 1
    chaos.uninstall()

    inj = chaos.install(_chaos_cfg(store_slow_p=1.0, store_slow_max_s=0.01),
                        scope="srv")
    assert s.get("obj.bin") == data  # slow but correct
    assert inj.counts["store_read_slow"] == 1


def test_store_get_to_file_routes_through_read_faults(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path / "store")
    s.put("obj.bin", b"a" * 1000)
    chaos.install(_chaos_cfg(store_bitflip_p=1.0), scope="srv")
    dst = tmp_path / "out.bin"
    s.get_to_file("obj.bin", dst)
    fetched = dst.read_bytes()
    assert len(fetched) == 1000 and fetched != b"a" * 1000


def test_store_fault_max_corrupts_exactly_one_object(tmp_path):
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    objs = {f"o{i}.bin": bytes([i]) * 512 for i in range(8)}
    for k, v in objs.items():
        s.put(k, v)
    inj = chaos.install(
        _chaos_cfg(store_bitflip_p=1.0, store_fault_max=1), scope="srv"
    )
    corrupted = [k for k, v in objs.items() if s.get(k) != v]
    assert len(corrupted) == 1  # the cap makes "exactly one" deterministic
    assert inj.counts["store_read_bitflip"] == 1


def test_store_fault_max_gates_corruption_not_delays(tmp_path):
    """The cap bounds CORRUPTING faults only: with slow armed alongside,
    delays keep firing (and never consume the budget), while exactly one
    object comes back corrupt."""
    from photon_tpu.checkpoint.store import FileStore

    s = FileStore(tmp_path)
    objs = {f"o{i}.bin": bytes([i]) * 64 for i in range(4)}
    for k, v in objs.items():
        s.put(k, v)
    inj = chaos.install(
        _chaos_cfg(store_slow_p=1.0, store_slow_max_s=0.001,
                   store_bitflip_p=1.0, store_fault_max=1), scope="srv"
    )
    corrupted = [k for k, v in objs.items() if s.get(k) != v]
    assert len(corrupted) == 1
    assert inj.counts["store_read_bitflip"] == 1
    assert inj.counts["store_read_slow"] == 4  # delays are never capped


# ---------------------------------------------------------------------------
# chaos → integrity end-to-end: corrupt checkpoint detected at resume
# ---------------------------------------------------------------------------


def test_chaos_bitflip_checkpoint_skipped_at_resume(tmp_path):
    """A chaos bit-flip during round-3's checkpoint write is caught by the
    manifest checksums and resume falls back to round 2."""
    from photon_tpu.checkpoint import FileStore, ServerCheckpointManager
    from photon_tpu.codec import ParamsMetadata

    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    arrays = [np.ones((8, 8), dtype=np.float32)]
    meta = ParamsMetadata.from_ndarrays(["w"], arrays)
    mgr.save_round(1, meta, arrays, {}, {"round": 1})
    mgr.save_round(2, meta, arrays, {}, {"round": 2})
    # round 3 writes under chaos: every object bit-flipped AFTER the
    # manifest CRCs were computed over the true bytes
    chaos.install(_chaos_cfg(store_bitflip_p=1.0), scope="srv")
    mgr.save_round(3, meta, arrays, {}, {"round": 3})
    chaos.uninstall()
    assert not mgr.verify_round(3)
    with pytest.warns(UserWarning, match="checksum"):
        assert mgr.resolve_resume_round(-1) == 2


def test_chaos_bitflipped_read_skipped_at_resume(tmp_path):
    """ISSUE 8 satellite: every checkpoint on disk is VALID, but one object
    read comes back bit-flipped (bad RAM / flaky NFS — the injector is
    seeded and capped to corrupt exactly one read). The corruption must
    surface as a manifest checksum error — the round is skipped with a
    warning and resume falls back — never a silently garbage param load."""
    from photon_tpu.checkpoint import FileStore, ServerCheckpointManager
    from photon_tpu.codec import ParamsMetadata

    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    arrays = [np.ones((8, 8), dtype=np.float32)]
    meta = ParamsMetadata.from_ndarrays(["w"], arrays)
    for r in (1, 2, 3):
        mgr.save_round(r, meta, arrays, {}, {"round": r})

    # a fresh manager (cold verification memo, the resume shape) resolving
    # under the read-plane injector: round 3's first read is corrupted →
    # checksum skip-and-warn → round 2, whose reads (past the cap) are clean
    resumer = ServerCheckpointManager(FileStore(tmp_path), "run1")
    inj = chaos.install(
        _chaos_cfg(store_bitflip_p=1.0, store_fault_max=1), scope="srv"
    )
    with pytest.warns(UserWarning, match="checksum"):
        resumed = resumer.resolve_resume_round(-1)
    assert resumed == 2
    assert inj.counts["store_read_bitflip"] == 1
    _, params, _, server_state = resumer.load_round(resumed)
    np.testing.assert_array_equal(params[0], arrays[0])
    assert server_state == {"round": 2}

    # the skipped round was intact at rest all along: without the read
    # fault a fresh manager verifies it clean
    chaos.uninstall()
    assert ServerCheckpointManager(FileStore(tmp_path), "run1").verify_round(3)


# ---------------------------------------------------------------------------
# crash-hook placement (recording crash_fn, no process exits)
# ---------------------------------------------------------------------------


def test_crash_hooks_fire_pre_and_mid_fit(tmp_path):
    for phase in ("pre-fit", "mid-fit"):
        cfg = make_cfg(tmp_path, n_rounds=1)
        cfg.photon.chaos.enabled = True
        cfg.photon.chaos.crash_phase = phase
        cfg.photon.chaos.crash_round = 1
        app = make_app(cfg, tmp_path)
        recorded = []
        # re-install over the ServerApp's default installation to swap in a
        # recording crash_fn (in-process agents share the server's injector)
        chaos.install(cfg.photon.chaos, scope="server", crash_fn=recorded.append)
        app.run()
        app.driver.shutdown()
        assert recorded and set(recorded) == {137}, phase


def test_serve_deduplicates_repeated_envelopes(tmp_path):
    """A chaos-duplicated FitIns must not run the fit twice — the second
    run would double-advance per-cid loader/optimizer state."""
    from photon_tpu.federation import NodeAgent, ParamTransport
    from photon_tpu.federation.messages import Query

    cfg = make_cfg(tmp_path)
    agent = NodeAgent(cfg, "node0", lambda: ParamTransport("inline"))
    handled = []
    orig = agent.handle
    agent.handle = lambda msg: (handled.append(msg), orig(msg))[1]

    class _StubConn:
        def __init__(self, envs):
            self.envs = list(envs)
            self.sent = []

        def recv(self):
            if not self.envs:
                raise EOFError
            return self.envs.pop(0)

        def send(self, obj):
            self.sent.append(obj)

    ping = Envelope(Query("ping"), 5)
    conn = _StubConn([ping, ping, Envelope(Query("ping"), 6)])
    agent.serve(conn)
    agent.runtime.close()
    assert len(handled) == 2  # mids 5 and 6 once each; the duplicate dropped
    assert [e.msg_id for e in conn.sent] == [5, 6]


def test_pre_reply_crash_hook_fires_in_serve(tmp_path):
    """pre-reply is the serve-loop's window: work done, result not yet on
    the wire. An error FitRes counts — the reply is what matters."""
    from photon_tpu.federation import NodeAgent, ParamTransport
    from photon_tpu.federation.messages import FitIns

    cfg = make_cfg(tmp_path)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "pre-reply"
    recorded = []
    chaos.install(cfg.photon.chaos, scope="node0", crash_fn=recorded.append)
    agent = NodeAgent(cfg, "node0", lambda: ParamTransport("inline"))

    class _StubConn:
        def __init__(self, envs):
            self.envs = list(envs)
            self.sent = []

        def recv(self):
            if not self.envs:
                raise EOFError
            return self.envs.pop(0)

        def send(self, obj):
            self.sent.append(obj)

    # params=None with no prior broadcast → an error FitRes, cheaply
    ins = FitIns(server_round=1, cids=[0], params=None, local_steps=1,
                 server_steps_cumulative=0)
    conn = _StubConn([Envelope(ins, 1)])
    agent.serve(conn)
    agent.runtime.close()
    assert recorded == [137]
    assert len(conn.sent) == 1  # the recording crash_fn returned; reply sent


# ---------------------------------------------------------------------------
# the acceptance e2e: SIGKILL a node mid-fit → budget absorbs → readmitted
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_crash_midfit_node_readmitted_e2e(tmp_path):
    """ISSUE 3 acceptance: under ``photon.chaos`` a node is SIGKILLed
    (``os._exit``) mid-fit in round 1. The round must complete within the
    failure budget, the multiprocess supervisor respawns the node, the
    server re-broadcasts and readmits it, and subsequent rounds aggregate
    full capacity from it again."""
    from photon_tpu.checkpoint import FileStore
    from photon_tpu.federation import MultiprocessDriver, ParamTransport, ServerApp

    cfg = make_cfg(
        tmp_path, n_rounds=3, n_total_clients=2, n_clients_per_round=2,
        local_steps=1, accept_failures_cnt=1,
    )
    cfg.photon.comm_stack.shm = False
    cfg.photon.comm_stack.objstore = True  # cross-process bulk plane
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 1
    cfg.photon.chaos.crash_node_id = "node0"
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash_marker")
    cfg.validate()

    driver = MultiprocessDriver(cfg, n_nodes=2, platform="cpu", n_cpu_devices=1)
    store = FileStore(cfg.photon.save_path + "/store")
    app = ServerApp(cfg, driver, ParamTransport("objstore", store=store))
    try:
        history = app.run()
    finally:
        driver.shutdown()

    assert (tmp_path / "crash_marker").exists(), "the chaos crash never fired"
    n_clients = dict(history.series("server/n_clients"))
    # round 1 completed: the killed node's cid was retried within the budget
    assert n_clients[1] == 2.0
    # the node was readmitted (respawn + re-broadcast) and later rounds run
    # at FULL capacity — a dead node may not halve the fleet forever
    assert n_clients[2] == 2.0 and n_clients[3] == 2.0
    assert history.cumulative("server/nodes_readmitted") >= 1.0
    assert history.latest("server/nodes_live") == 2.0
    # the driver counted the respawn in its hello stats
    assert driver.hello_stats().get("node0", {}).get("reconnects", 0) >= 1
    # no round was recorded failed
    assert not history.series("server/round_failed")


@pytest.mark.slow
def test_soak_random_failures_across_rounds(tmp_path):
    """Sustained randomized failures across 6 rounds — now ALSO under both
    photon-lint dynamic detectors (ISSUE 6): every lock the server, driver,
    host pool and agents create is order-tracked (teardown fails on any
    potential-deadlock cycle), and after a 3-round warmup the retrace
    sentinel fails the run if a steady-state round compiles anything — the
    failure/retry/recovery paths must not silently retrace."""
    from photon_tpu.analysis import runtime as lint_rt

    lock_rec = lint_rt.install_lock_order()
    sentinel = lint_rt.install_retrace_sentinel()
    sentinel.mark_steady_after(3)  # server/round hook: rounds 4-6 steady
    try:
        _soak_body(tmp_path)
        assert sentinel.steady, "round hook never fired"
        sentinel.check()
        lock_rec.check()
    finally:
        lint_rt.uninstall_retrace_sentinel()
        lint_rt.uninstall_lock_order()


def _soak_body(tmp_path):
    n_rounds = 6
    cfg = make_cfg(
        tmp_path,
        n_rounds=n_rounds,
        n_total_clients=4,
        n_clients_per_round=3,
        accept_failures_cnt=1,   # one PERSISTENT failure tolerated per round
        ignore_failed_rounds=True,
    )
    app = make_app(cfg, tmp_path, n_nodes=2)

    rng = random.Random(1234)
    chaos = {"first_attempt_fails": set(), "hard_fails": set()}
    blackout_rounds = set()
    for rnd in range(1, n_rounds + 1):
        # every round: one cid flakes once (must be retried and aggregated);
        # some rounds: ONE cid fails both attempts (absorbed by the budget);
        # some rounds: THREE of four cids hard-fail — with 3 sampled per
        # round at least two are hit, the budget (1) is exceeded, and the
        # ignore_failed_rounds recovery path must carry the run onward
        chaos["first_attempt_fails"].add((rnd, rng.randrange(4)))
        roll = rng.random()
        if roll < 0.3:
            blackout_rounds.add(rnd)
            for cid in rng.sample(range(4), 3):
                chaos["hard_fails"].add((rnd, cid))
        elif roll < 0.6:
            chaos["hard_fails"].add((rnd, rng.randrange(4)))
    assert blackout_rounds, "seed must schedule at least one blackout round"

    attempts: dict[tuple[int, int], int] = {}
    for agent in app.driver._agents.values():
        orig_fit = agent.runtime.fit

        def fit(ins, cid, _orig=orig_fit):
            key = (ins.server_round, cid)
            attempts[key] = attempts.get(key, 0) + 1
            if key in chaos["hard_fails"]:
                return FitRes(ins.server_round, cid, None, error="chaos-hard")
            if key in chaos["first_attempt_fails"] and attempts[key] == 1:
                return FitRes(ins.server_round, cid, None, error="chaos-flaky")
            return _orig(ins, cid)

        agent.runtime.fit = fit

    history = app.run()
    app.driver.shutdown()

    rounds_failed = {r for r, _ in history.series("server/round_failed")}
    rounds_ok = [r for r, _ in history.series("server/n_clients")]
    assert len(rounds_ok) + len(rounds_failed) == n_rounds
    # blackout rounds (>=2 of 3 sampled cids hard-failing) MUST exceed the
    # budget and be recorded failed — proving ignore_failed_rounds recovery
    # actually ran, not just that chaos was survivable
    assert blackout_rounds <= rounds_failed, (blackout_rounds, rounds_failed)
    assert rounds_ok, "every round failed — chaos schedule too aggressive"
    # flaky-only rounds MUST complete (retry-once absorbs the first failure)
    for rnd in range(1, n_rounds + 1):
        sampled_hard = any(r == rnd for r, _ in chaos["hard_fails"])
        if not sampled_hard:
            assert rnd in rounds_ok, f"round {rnd} had only flaky failures"
    # training signal flowed every completed round
    for rnd, norm in history.series("server/pseudo_grad_norm"):
        assert np.isfinite(norm) and norm > 0
    # steps advance exactly once per completed round
    steps = dict(history.series("server/steps_cumulative"))
    assert app.server_steps_cumulative == len(rounds_ok) * cfg.fl.local_steps
    assert steps[rounds_ok[-1]] == app.server_steps_cumulative
    # retried flaky cids were attempted at least twice in completed rounds
    for (rnd, cid) in chaos["first_attempt_fails"]:
        if rnd in rounds_ok and (rnd, cid) not in chaos["hard_fails"]:
            # only sampled cids get attempts; if sampled, retry happened
            if (rnd, cid) in attempts:
                assert attempts[(rnd, cid)] >= 2, (rnd, cid)
