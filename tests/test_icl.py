"""ICL gauntlet harness tests: task parsing, MC scoring correctness with a
rigged model, gauntlet aggregation with random-baseline subtraction."""

import json

import jax
import jax.numpy as jnp

from photon_tpu.data.tokenizer import ByteTokenizer
from photon_tpu.eval import ICLTask, make_logprob_fn, run_gauntlet

VOCAB = 257
SEQ = 32


def _apply(params, tokens):
    """Deterministic fake model (jit-traceable): next byte = current + 1."""
    nxt = (tokens + 1) % VOCAB
    return 20.0 * jax.nn.one_hot(nxt, VOCAB, dtype=jnp.float32) - 10.0


def test_task_from_jsonl(tmp_path):
    rows = [{"query": "q", "choices": ["a", "b"], "gold": 0}] * 3
    p = tmp_path / "mc.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    task = ICLTask.from_jsonl(p, category="knowledge")
    assert task.kind == "multiple_choice"
    assert task.random_baseline == 0.5
    assert task.name == "mc"


def test_mc_scoring_prefers_predictable_continuation(tmp_path):
    tok = ByteTokenizer()
    # bigram model loves ascending byte runs: "abcd" -> "efgh" is predictable
    rows = [
        {"query": "abcd", "choices": ["efgh", "zzzz"], "gold": 0},
        {"query": "mnop", "choices": ["xxxx", "qrst"], "gold": 1},
    ]
    task = ICLTask("asc", "multiple_choice", rows, "synthetic", 0.5)
    out = run_gauntlet([task], tok, _apply, params=None, seq_len=SEQ, batch_size=8)
    assert out["icl/asc/accuracy"] == 1.0
    # baseline-subtracted, rescaled: (1.0 - 0.5)/0.5 = 1.0
    assert out["icl/category/synthetic"] == 1.0
    assert out["icl/average"] == 1.0


def test_lm_task_logprob(tmp_path):
    tok = ByteTokenizer()
    rows = [{"context": "abc", "continuation": "def"}]
    task = ICLTask("lm", "language_modeling", rows)
    logprob_fn = make_logprob_fn(_apply, None, SEQ)
    from photon_tpu.eval.icl import evaluate_task

    res = evaluate_task(task, tok, logprob_fn, SEQ, batch_size=4)
    # perfectly predicted continuation: logprob/token ≈ log softmax(10 vs -10) ≈ 0
    assert res["logprob_per_token"] > -0.01


def test_gauntlet_floor_at_zero():
    tok = ByteTokenizer()
    rows = [{"query": "abcd", "choices": ["zzzz", "efgh"], "gold": 0}]  # model picks wrong
    task = ICLTask("bad", "multiple_choice", rows, "synthetic", 0.5)
    out = run_gauntlet([task], tok, _apply, None, seq_len=SEQ, batch_size=8)
    assert out["icl/bad/accuracy"] == 0.0
    assert out["icl/category/synthetic"] == 0.0  # clamped, not negative
