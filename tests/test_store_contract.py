"""Object-store contract suite (VERDICT r2 item 8): every backend must obey
the same semantics the round loop and checkpoint managers rely on — atomic
visibility, path-component prefix listing, wait_for polling.

Runs against FileStore and against S3Store driven by an in-memory fake of the
boto3 client surface (boto3 itself is optional; the fake exercises S3Store's
real key/prefix/pagination logic either way). Reference behavior being
matched: ``photon/server/s3_utils.py:730-933``.
"""

import threading
import time

import pytest

from photon_tpu.checkpoint.store import FileStore, ObjectStore, S3Store, make_store


class FakeS3Client:
    """In-memory boto3-S3-client lookalike (only the surface S3Store uses),
    with V2-style pagination to exercise the pagination path."""

    PAGE = 3  # tiny page size so multi-page listing is actually tested

    def __init__(self):
        self.blobs: dict[str, bytes] = {}
        self.lock = threading.Lock()

    def put_object(self, Bucket, Key, Body):
        with self.lock:
            self.blobs[Key] = bytes(Body)

    def get_object(self, Bucket, Key):
        class _Body:
            def __init__(self, data):
                self._data = data

            def read(self):
                return self._data

        if Key not in self.blobs:
            raise self._not_found()
        return {"Body": _Body(self.blobs[Key])}

    def head_object(self, Bucket, Key):
        if Key not in self.blobs:
            raise self._not_found()
        return {}

    def delete_object(self, Bucket, Key):
        with self.lock:
            self.blobs.pop(Key, None)

    def copy_object(self, Bucket, Key, CopySource):
        self.blobs[Key] = self.blobs[CopySource["Key"]]

    def get_paginator(self, op):
        assert op == "list_objects_v2"
        outer = self

        class _Pager:
            def paginate(self, Bucket, Prefix):
                keys = sorted(k for k in outer.blobs if k.startswith(Prefix))
                for i in range(0, len(keys), outer.PAGE):
                    yield {"Contents": [{"Key": k} for k in keys[i : i + outer.PAGE]]}
                if not keys:
                    yield {}

        return _Pager()

    @staticmethod
    def _not_found():
        e = Exception("NoSuchKey")
        e.response = {"Error": {"Code": "404"}}
        return e


@pytest.fixture(params=["file", "s3"])
def store(request, tmp_path) -> ObjectStore:
    if request.param == "file":
        return FileStore(tmp_path / "store")
    return S3Store("bucket", prefix="runs/test", client=FakeS3Client())


def test_put_get_roundtrip_and_overwrite(store):
    store.put("a/b/blob.bin", b"v1")
    assert store.get("a/b/blob.bin") == b"v1"
    store.put("a/b/blob.bin", b"v2-longer")
    assert store.get("a/b/blob.bin") == b"v2-longer"


def test_exists_lifecycle(store):
    assert not store.exists("x")
    store.put("x", b"1")
    assert store.exists("x")
    store.delete("x")
    assert not store.exists("x")


def test_delete_is_idempotent(store):
    store.delete("never/existed")  # must not raise


def test_delete_directory_like(store):
    store.put("run/1/a", b"1")
    store.put("run/1/b", b"2")
    store.put("run/2/a", b"3")
    store.delete("run/1")
    assert store.list("run") == ["run/2/a"]


def test_list_prefix_is_path_component_based(store):
    """'a/b' must not match sibling 'a/bc' (string-prefix bleed)."""
    store.put("a/b/one", b"1")
    store.put("a/b/two", b"2")
    store.put("a/bc/three", b"3")
    assert store.list("a/b") == ["a/b/one", "a/b/two"]
    assert store.list("a") == ["a/b/one", "a/b/two", "a/bc/three"]
    assert store.list("missing") == []


def test_list_many_pages(store):
    keys = [f"p/{i:03d}" for i in range(10)]
    for k in keys:
        store.put(k, b"x")
    assert store.list("p") == keys  # FakeS3Client pages at 3 → 4 pages


def test_copy(store):
    store.put("src", b"payload")
    store.copy("src", "deep/dst")
    assert store.get("deep/dst") == b"payload"
    assert store.get("src") == b"payload"


def test_wait_for_sees_concurrent_writer(store):
    t = threading.Timer(0.15, lambda: store.put("late", b"here"))
    t.start()
    store.wait_for("late", timeout=5.0, poll=0.01)
    assert store.get("late") == b"here"
    t.join()


def test_wait_for_times_out(store):
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        store.wait_for("never", timeout=0.2, poll=0.02)
    assert time.monotonic() - t0 < 2.0


def test_get_missing_raises(store):
    with pytest.raises(Exception):
        store.get("missing-key")


# -- backend-specific ------------------------------------------------------


def test_filestore_tmp_files_invisible(tmp_path):
    """Atomic-visibility detail: in-flight temp files never appear in list()
    or exists() (reference relies on S3 atomic PUT; FileStore gets the same
    property from tmp+rename)."""
    fs = FileStore(tmp_path / "s")
    (fs.root / ".blob.tmp-999").write_bytes(b"partial")
    assert fs.list("") == []
    assert not fs.exists("blob")


def test_filestore_rejects_escaping_keys(tmp_path):
    fs = FileStore(tmp_path / "s")
    with pytest.raises(ValueError):
        fs.put("../outside", b"x")


def test_s3store_prefix_isolation():
    client = FakeS3Client()
    a = S3Store("b", prefix="run-a", client=client)
    b = S3Store("b", prefix="run-b", client=client)
    a.put("k", b"A")
    b.put("k", b"B")
    assert a.get("k") == b"A" and b.get("k") == b"B"
    assert a.list("") == ["k"]


def test_make_store_dispatch(tmp_path):
    assert isinstance(make_store(str(tmp_path / "x")), FileStore)
    assert isinstance(make_store(f"file://{tmp_path}/y"), FileStore)
    try:
        import boto3  # noqa: F401

        assert isinstance(make_store("s3://bucket/prefix"), S3Store)
    except ImportError:
        with pytest.raises(NotImplementedError, match="boto3"):
            make_store("s3://bucket/prefix")
    with pytest.raises(ValueError):
        make_store("s3://")
