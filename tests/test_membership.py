"""Elastic membership: reconnect backoff timing (injected clock/rng), the
live → suspect → dead → readmitted state machine, ping sweeps over real
drivers, and the per-round liveness KPIs.

These are the fast tier-1 half of ISSUE 3's robustness coverage; the
process-killing e2e lives in test_chaos.py (slow)."""

import pytest

from photon_tpu.federation.membership import (
    DEAD,
    LIVE,
    SUSPECT,
    LivenessTracker,
    ReconnectPolicy,
    hello_backoff_total,
)
from photon_tpu.federation.messages import Ack, FitRes, ParamPointer, Query
from tests.test_federation import make_app, make_cfg

pytestmark = pytest.mark.chaos  # rides `make chaos` (and, being fast, tier-1)


# ---------------------------------------------------------------------------
# ReconnectPolicy
# ---------------------------------------------------------------------------


class _FixedRng:
    """rng whose .random() replays a fixed sequence (wraps around)."""

    def __init__(self, vals):
        self.vals = list(vals)
        self.i = 0

    def random(self):
        v = self.vals[self.i % len(self.vals)]
        self.i += 1
        return v


def test_backoff_exponential_and_capped():
    p = ReconnectPolicy(base_s=0.5, max_s=8.0, jitter=0.0)
    assert [p.delay(k) for k in range(6)] == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0]


def test_backoff_jitter_bounds_and_determinism():
    # rng pinned to the extremes: jitter must stay within ±25%
    lo = ReconnectPolicy(base_s=1.0, max_s=64.0, jitter=0.25, rng=_FixedRng([0.0]))
    hi = ReconnectPolicy(base_s=1.0, max_s=64.0, jitter=0.25, rng=_FixedRng([1.0 - 1e-12]))
    for k in range(5):
        raw = min(64.0, 2.0**k)
        assert lo.delay(k) == pytest.approx(raw * 0.75)
        assert hi.delay(k) == pytest.approx(raw * 1.25, rel=1e-6)
    # same seed sequence → same delays (the supervisor's schedule is replayable)
    a = ReconnectPolicy(base_s=1.0, max_s=64.0, jitter=0.25, rng=_FixedRng([0.3, 0.9, 0.1]))
    b = ReconnectPolicy(base_s=1.0, max_s=64.0, jitter=0.25, rng=_FixedRng([0.3, 0.9, 0.1]))
    assert [a.delay(k) for k in range(6)] == [b.delay(k) for k in range(6)]


def test_backoff_huge_attempt_never_overflows():
    # unlimited retries (max_attempts=0) reach arbitrarily large attempt
    # counts: 2.0**attempt must be clamped, not raise OverflowError
    p = ReconnectPolicy(base_s=0.5, max_s=30.0, jitter=0.0, max_attempts=0)
    assert p.delay(5000) == 30.0


def test_backoff_exhaustion():
    p = ReconnectPolicy(max_attempts=3)
    assert not p.exhausted(2)
    assert p.exhausted(3)
    unlimited = ReconnectPolicy(max_attempts=0)
    assert not unlimited.exhausted(10_000)


def test_backoff_from_config(tmp_path):
    cfg = make_cfg(tmp_path)
    cfg.photon.membership.reconnect_backoff_base_s = 0.1
    cfg.photon.membership.reconnect_backoff_max_s = 1.0
    cfg.photon.membership.reconnect_backoff_jitter = 0.0
    cfg.photon.membership.reconnect_max_attempts = 7
    p = ReconnectPolicy.from_config(cfg.photon.membership)
    assert (p.base_s, p.max_s, p.jitter, p.max_attempts) == (0.1, 1.0, 0.0, 7)


def test_membership_config_validation(tmp_path):
    cfg = make_cfg(tmp_path)
    cfg.photon.membership.dead_after_misses = 0
    with pytest.raises(ValueError, match="suspect_after_misses"):
        cfg.validate()
    cfg = make_cfg(tmp_path)
    cfg.photon.membership.reconnect_backoff_jitter = 1.5
    with pytest.raises(ValueError, match="jitter"):
        cfg.validate()


# ---------------------------------------------------------------------------
# LivenessTracker state machine
# ---------------------------------------------------------------------------


def test_liveness_state_machine():
    t = LivenessTracker(suspect_after_misses=1, dead_after_misses=3)
    t.register_present(["n0"])
    assert t.nodes["n0"].state == LIVE
    t.observe_miss("n0")
    assert t.nodes["n0"].state == SUSPECT
    t.observe_miss("n0")
    assert t.nodes["n0"].state == SUSPECT
    t.observe_miss("n0")
    assert t.nodes["n0"].state == DEAD
    # a reply resets everything and counts the readmission
    t.observe_alive("n0")
    assert t.nodes["n0"].state == LIVE
    assert t.nodes["n0"].misses == 0
    assert t.readmitted_total == 1


def test_register_present_readmits_dead_id_after_absence():
    t = LivenessTracker(suspect_after_misses=1, dead_after_misses=2)
    t.register_present(["n0"])
    t.observe_miss("n0")
    t.observe_miss("n0")
    assert t.counts()[DEAD] == 1
    # the id actually LEAVES the registry (TCP eviction)...
    assert t.register_present([]) == []
    # ...and re-registers: that's a readmission
    assert t.register_present(["n0"]) == ["n0"]
    assert t.counts() == {LIVE: 1, SUSPECT: 0, DEAD: 0}
    m = t.round_metrics(hello_backoff_s=2.5)
    assert m["server/nodes_live"] == 1.0
    assert m["server/nodes_readmitted"] == 1.0
    assert m["server/reconnect_backoff_s"] == 2.5
    # per-round readmission counter resets after the snapshot
    assert t.round_metrics()["server/nodes_readmitted"] == 0.0


def test_wedged_but_connected_node_stays_dead():
    """A node whose socket stays open but never answers pings must go dead
    and STAY dead — continued registry presence is not a reappearance, and
    the readmission KPI must not oscillate."""
    t = LivenessTracker(suspect_after_misses=1, dead_after_misses=2,
                        ping_timeout_s=0.05)
    d = _ScriptedDriver({"n0": "silent"})
    t.sweep(d)
    t.sweep(d)
    assert t.nodes["n0"].state == DEAD
    for _ in range(3):  # rounds keep registering + sweeping: no flapping
        assert t.register_present(d.node_ids()) == []
        assert t.sweep(d) == []
        assert t.nodes["n0"].state == DEAD
    assert t.readmitted_total == 0
    # it finally answers a ping: THAT readmits
    d.behaviors["n0"] = "ok"
    assert t.sweep(d) == ["n0"]
    assert t.nodes["n0"].state == LIVE and t.readmitted_total == 1


def test_note_readmitted_always_counts():
    # the window sees deaths (EOF dead-letters) before the sweep moves
    # states, so readmission must count even from LIVE
    t = LivenessTracker()
    t.register_present(["n0"])
    t.note_readmitted("n0")
    assert t.readmitted_total == 1


class _ScriptedDriver:
    """Driver double: scripted per-node ping behavior, no sockets."""

    def __init__(self, behaviors):
        self.behaviors = dict(behaviors)  # nid -> "ok" | "dead" | "silent"
        self._mid = iter(range(10_000))
        self._replies = []

    def node_ids(self):
        return sorted(self.behaviors)

    def send(self, nid, msg):
        mid = next(self._mid)
        b = self.behaviors[nid]
        if b == "ok":
            self._replies.append((nid, mid, Ack(ok=True, node_id=nid)))
        elif b == "dead":
            self._replies.append((nid, mid, Ack(ok=False, detail="node died", node_id=nid)))
        # "silent": no reply ever
        return mid

    def recv_any(self, timeout=None):
        if not self._replies:
            raise TimeoutError("nothing")
        return self._replies.pop(0)


def test_sweep_transitions_and_stale_drain():
    clock = [0.0]
    t = LivenessTracker(suspect_after_misses=1, dead_after_misses=2,
                        ping_timeout_s=10.0, clock=lambda: clock[0])
    d = _ScriptedDriver({"n0": "ok", "n1": "silent", "n2": "dead"})
    t.sweep(d)
    assert t.nodes["n0"].state == LIVE
    assert t.nodes["n1"].state == SUSPECT
    assert t.nodes["n2"].state == SUSPECT
    t.sweep(d)
    assert t.nodes["n1"].state == DEAD
    assert t.nodes["n2"].state == DEAD
    # n1 comes back: the answered ping readmits it (its id never left the
    # registry, so presence alone could not)
    d.behaviors["n1"] = "ok"
    readmitted = t.sweep(d)
    assert "n1" in readmitted
    assert t.nodes["n1"].state == LIVE
    # a node known to the tracker but GONE from the registry misses too
    del d.behaviors["n2"]
    t.sweep(d)
    assert t.nodes["n2"].state == DEAD


def test_sweep_hands_stale_replies_to_callback():
    class _StaleDriver(_ScriptedDriver):
        def __init__(self):
            super().__init__({"n0": "ok"})
            # a late FitRes from a previous round sits in the queue with a
            # mid the sweep never issued
            ptr = ParamPointer("inline", "", '{"names": [], "shapes": [], "dtypes": []}', inline=[])
            self._replies.append(("n0", 99_999, FitRes(1, 0, ptr)))

    freed = []
    t = LivenessTracker()
    t.sweep(_StaleDriver(), on_stale=freed.append)
    assert len(freed) == 1 and isinstance(freed[0], FitRes)
    assert t.nodes["n0"].state == LIVE


def test_hello_backoff_total():
    assert hello_backoff_total(None) == 0.0
    assert hello_backoff_total({}) == 0.0
    assert hello_backoff_total(
        {"n0": {"reconnects": 2, "backoff_s": 1.5}, "n1": {"backoff_s": 0.5}}
    ) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# ServerApp integration (in-process driver)
# ---------------------------------------------------------------------------


def test_round_loop_records_liveness_kpis(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=2)
    app = make_app(cfg, tmp_path)
    history = app.run()
    app.driver.shutdown()
    for key in ("server/nodes_live", "server/nodes_suspect", "server/nodes_dead",
                "server/nodes_readmitted", "server/reconnect_backoff_s"):
        assert len(history.series(key)) == 2, key
    assert history.latest("server/nodes_live") == 2.0
    assert history.latest("server/nodes_dead") == 0.0
    assert history.latest("server/nodes_readmitted") == 0.0


def test_broadcast_frees_stale_late_replies(tmp_path):
    """A late FitRes draining during the NEXT round's broadcast (possible
    whenever the ping sweep is off) must free its transport segment, not
    silently leak it."""
    cfg = make_cfg(tmp_path, n_rounds=1)
    cfg.photon.membership.enabled = False
    app = make_app(cfg, tmp_path)
    stale_ptr = ParamPointer(
        "inline", "", '{"names": [], "shapes": [], "dtypes": []}', inline=[]
    )
    freed = []
    app.transport.free = freed.append
    app.driver._replies.insert(0, ("node0", 99_999, FitRes(1, 0, stale_ptr)))
    app.broadcast_parameters(1)
    assert stale_ptr in freed
    app.driver.shutdown()


def test_sweep_skipped_when_disabled(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=1)
    cfg.photon.membership.enabled = False
    app = make_app(cfg, tmp_path)
    pings = []
    orig_send = app.driver.send

    def send(nid, msg):
        if isinstance(msg, Query) and msg.action == "ping":
            pings.append(nid)
        return orig_send(nid, msg)

    app.driver.send = send
    history = app.run()
    app.driver.shutdown()
    assert not pings
    # KPIs still recorded (register_present keeps the registry view fresh)
    assert history.latest("server/nodes_live") == 2.0
