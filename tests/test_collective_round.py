"""Collective federated rounds match the driver topology exactly.

The marquee-path integration test (SURVEY §7 stage 6): two
``jax.distributed`` processes (2 clients each) run TWO full federated
rounds entirely over XLA collectives (``CollectiveFedRunner``: local
ClientRuntime fits → client-axis psum average → replica strategy update),
and the resulting global parameters must match an ``InProcessDriver``
ServerApp run of the same config to float tolerance — proving the DCN
plane is a drop-in replacement for the pointer plane, not a lookalike.
"""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from photon_tpu.config.schema import Config

CHILD = r"""
import json, sys
import jax

pid = int(sys.argv[1]); port = sys.argv[2]; cfg_path = sys.argv[3]; out_path = sys.argv[4]
jax.config.update("jax_platforms", "cpu")
from photon_tpu.utils.compat import set_cpu_device_count
set_cpu_device_count(2)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import numpy as np
from photon_tpu.config.schema import Config
from photon_tpu.federation.collective_round import CollectiveFedRunner, partition_cids

cfg = Config.from_yaml(cfg_path)
cfg.photon.save_path = cfg.photon.save_path + f"/proc{pid}"
cfg.validate()
cids = partition_cids(cfg.fl.n_total_clients, 2, pid)
runner = CollectiveFedRunner(cfg, cids)
history = runner.run()
np.savez(out_path, *runner.strategy.current_parameters)
with open(out_path + ".metrics.json", "w") as f:
    json.dump({
        "steps": runner.server_steps_cumulative,
        "eval_loss": history.latest("server/eval_loss"),
        "pseudo_grad_norm": history.latest("server/pseudo_grad_norm"),
    }, f)
print(json.dumps({"pid": pid, "cids": cids}), flush=True)
"""


def _cfg(tmp_path, strategy="fedavg", momenta=False) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 4
    cfg.train.device_microbatch_size = 4
    cfg.fl.n_total_clients = 4
    cfg.fl.n_clients_per_round = 4  # collective mode = full participation
    cfg.fl.n_rounds = 2
    cfg.fl.local_steps = 2
    cfg.fl.eval_interval_rounds = 2
    cfg.fl.strategy_name = strategy
    cfg.fl.server_learning_rate = 1.0 if strategy == "fedavg" else 0.01
    cfg.fl.aggregate_momenta = momenta
    if strategy == "fedadam":
        # adaptive updates divide by sqrt(v)+tau: with tau ~ 0 and v ~ 0 in
        # early rounds, fp32 reduction-order noise between the psum and the
        # host streaming average flips near-zero momenta signs and the
        # topologies legitimately diverge elementwise. A non-degenerate tau
        # keeps the comparison about the momenta PLUMBING, which is what
        # this test asserts.
        cfg.fl.server_tau = 1e-3
    cfg.dataset.synthetic = True
    cfg.photon.checkpoint = False
    cfg.photon.comm_stack.collective = True
    cfg.photon.comm_stack.shm = False
    cfg.run_uuid = "collective-round"
    return cfg


@pytest.mark.slow
@pytest.mark.parametrize(
    "strategy,momenta",
    [("fedavg", False), ("fedadam", True)],
    ids=["fedavg", "fedadam-momenta"],
)
def test_collective_rounds_match_driver_topology(tmp_path, strategy, momenta):
    from tests._helpers import free_port, subprocess_env

    # ---- oracle: the same config through the InProcessDriver ServerApp ----
    from photon_tpu.federated import build_app

    oracle_cfg = _cfg(tmp_path, strategy, momenta)
    oracle_cfg.photon.comm_stack.collective = False
    oracle_cfg.photon.comm_stack.shm = True
    oracle_cfg.photon.save_path = str(tmp_path / "oracle")
    oracle_cfg.validate()
    app = build_app(oracle_cfg, n_nodes=1)
    oracle_hist = app.run()
    oracle_params = app.strategy.current_parameters
    oracle_eval = oracle_hist.latest("server/eval_loss")
    app.driver.shutdown()

    # ---- collective: two real processes, two clients each ----------------
    cfg = _cfg(tmp_path, strategy, momenta)
    cfg.photon.save_path = str(tmp_path / "collective")
    cfg.validate()
    cfg_path = str(tmp_path / "collective.yaml")
    cfg.to_yaml(cfg_path)

    port = free_port()
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    outs = [tmp_path / f"params_{pid}.npz" for pid in range(2)]
    logs = [tmp_path / f"child_{pid}.log" for pid in range(2)]
    procs = []
    for pid in range(2):
        with logs[pid].open("w") as logf:
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), str(pid), str(port),
                     cfg_path, str(outs[pid])],
                    env=subprocess_env(), stdout=logf, stderr=subprocess.STDOUT,
                    text=True,
                )
            )
    for pid, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("collective round processes timed out")
        assert p.returncode == 0, logs[pid].read_text()[-3000:]

    # every controller must hold params equal to the oracle's up to fp32
    # reduction-order noise (psum tree-reduce vs the host streaming rescale
    # compound through the rounds: observed max |Δ| ≈ 1e-5 after 2 rounds)
    for out in outs:
        with np.load(out) as z:
            got = [z[k] for k in z.files]
        assert len(got) == len(oracle_params)
        for g, o in zip(got, oracle_params):
            np.testing.assert_allclose(g, o, rtol=1e-3, atol=5e-5)
    # ...and bitwise-identical to EACH OTHER (same psum on every controller)
    with np.load(outs[0]) as z0, np.load(outs[1]) as z1:
        for k in z0.files:
            np.testing.assert_array_equal(z0[k], z1[k])
    # fed eval over the collective matches the driver topology's eval
    for out in outs:
        m = json.loads(pathlib.Path(str(out) + ".metrics.json").read_text())
        assert m["eval_loss"] is not None and oracle_eval is not None
        np.testing.assert_allclose(m["eval_loss"], oracle_eval, rtol=1e-3)
