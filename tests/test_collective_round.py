"""Collective federated rounds match the driver topology exactly.

The marquee-path integration test (SURVEY §7 stage 6): two
``jax.distributed`` processes (2 clients each) run TWO full federated
rounds entirely over XLA collectives (``CollectiveFedRunner``: local
ClientRuntime fits → client-axis psum average → replica strategy update),
and the resulting global parameters must match an ``InProcessDriver``
ServerApp run of the same config to float tolerance — proving the DCN
plane is a drop-in replacement for the pointer plane, not a lookalike.
"""

import json
import pathlib
import subprocess
import sys

import jax
import numpy as np
import pytest

from photon_tpu.config.schema import Config

CHILD = r"""
import json, sys
import jax

pid = int(sys.argv[1]); port = sys.argv[2]; cfg_path = sys.argv[3]; out_path = sys.argv[4]
jax.config.update("jax_platforms", "cpu")
from photon_tpu.utils.compat import set_cpu_device_count
set_cpu_device_count(2)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import numpy as np
from photon_tpu.config.schema import Config
from photon_tpu.federation.collective_round import CollectiveFedRunner, partition_cids

cfg = Config.from_yaml(cfg_path)
cfg.photon.save_path = cfg.photon.save_path + f"/proc{pid}"
cfg.validate()
cids = partition_cids(cfg.fl.n_total_clients, 2, pid)
runner = CollectiveFedRunner(cfg, cids)
history = runner.run()
np.savez(out_path, *runner.strategy.current_parameters)
with open(out_path + ".metrics.json", "w") as f:
    json.dump({
        "steps": runner.server_steps_cumulative,
        "eval_loss": history.latest("server/eval_loss"),
        "pseudo_grad_norm": history.latest("server/pseudo_grad_norm"),
    }, f)
print(json.dumps({"pid": pid, "cids": cids}), flush=True)
"""


def _cfg(tmp_path, strategy="fedavg", momenta=False) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 4
    cfg.train.device_microbatch_size = 4
    cfg.fl.n_total_clients = 4
    cfg.fl.n_clients_per_round = 4  # collective mode = full participation
    cfg.fl.n_rounds = 2
    cfg.fl.local_steps = 2
    cfg.fl.eval_interval_rounds = 2
    cfg.fl.strategy_name = strategy
    cfg.fl.server_learning_rate = 1.0 if strategy == "fedavg" else 0.01
    cfg.fl.aggregate_momenta = momenta
    if strategy == "fedadam":
        # adaptive updates divide by sqrt(v)+tau: with tau ~ 0 and v ~ 0 in
        # early rounds, fp32 reduction-order noise between the psum and the
        # host streaming average flips near-zero momenta signs and the
        # topologies legitimately diverge elementwise. A non-degenerate tau
        # keeps the comparison about the momenta PLUMBING, which is what
        # this test asserts.
        cfg.fl.server_tau = 1e-3
    cfg.dataset.synthetic = True
    cfg.photon.checkpoint = False
    cfg.photon.comm_stack.collective = True
    cfg.photon.comm_stack.shm = False
    cfg.run_uuid = "collective-round"
    return cfg


@pytest.mark.slow
@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax 0.4.37 CPU backend can't run multiprocess computations "
    "(XLA: 'Multiprocess computations aren't implemented on the CPU "
    "backend') — the single-controller e2es below cover the plane here",
)
@pytest.mark.parametrize(
    "strategy,momenta",
    [("fedavg", False), ("fedadam", True)],
    ids=["fedavg", "fedadam-momenta"],
)
def test_collective_rounds_match_driver_topology(tmp_path, strategy, momenta):
    from tests._helpers import free_port, subprocess_env

    # ---- oracle: the same config through the InProcessDriver ServerApp ----
    from photon_tpu.federated import build_app

    oracle_cfg = _cfg(tmp_path, strategy, momenta)
    oracle_cfg.photon.comm_stack.collective = False
    oracle_cfg.photon.comm_stack.shm = True
    oracle_cfg.photon.save_path = str(tmp_path / "oracle")
    oracle_cfg.validate()
    app = build_app(oracle_cfg, n_nodes=1)
    oracle_hist = app.run()
    oracle_params = app.strategy.current_parameters
    oracle_eval = oracle_hist.latest("server/eval_loss")
    app.driver.shutdown()

    # ---- collective: two real processes, two clients each ----------------
    cfg = _cfg(tmp_path, strategy, momenta)
    cfg.photon.save_path = str(tmp_path / "collective")
    cfg.validate()
    cfg_path = str(tmp_path / "collective.yaml")
    cfg.to_yaml(cfg_path)

    port = free_port()
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    outs = [tmp_path / f"params_{pid}.npz" for pid in range(2)]
    logs = [tmp_path / f"child_{pid}.log" for pid in range(2)]
    procs = []
    for pid in range(2):
        with logs[pid].open("w") as logf:
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), str(pid), str(port),
                     cfg_path, str(outs[pid])],
                    env=subprocess_env(), stdout=logf, stderr=subprocess.STDOUT,
                    text=True,
                )
            )
    for pid, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("collective round processes timed out")
        assert p.returncode == 0, logs[pid].read_text()[-3000:]

    # every controller must hold params equal to the oracle's up to fp32
    # reduction-order noise (psum tree-reduce vs the host streaming rescale
    # compound through the rounds: observed max |Δ| ≈ 1e-5 after 2 rounds)
    for out in outs:
        with np.load(out) as z:
            got = [z[k] for k in z.files]
        assert len(got) == len(oracle_params)
        for g, o in zip(got, oracle_params):
            np.testing.assert_allclose(g, o, rtol=1e-3, atol=5e-5)
    # ...and bitwise-identical to EACH OTHER (same psum on every controller)
    with np.load(outs[0]) as z0, np.load(outs[1]) as z1:
        for k in z0.files:
            np.testing.assert_array_equal(z0[k], z1[k])
    # fed eval over the collective matches the driver topology's eval
    for out in outs:
        m = json.loads(pathlib.Path(str(out) + ".metrics.json").read_text())
        assert m["eval_loss"] is not None and oracle_eval is not None
        np.testing.assert_allclose(m["eval_loss"], oracle_eval, rtol=1e-3)


# ---------------------------------------------------------------------------
# ISSUE 7: device-resident aggregation plane e2e (single-controller,
# in-process — the multi-process parity e2e above stays the slow oracle)
# ---------------------------------------------------------------------------


def _plane_cfg(tmp_path, quantization, n_rounds=3):
    cfg = _cfg(tmp_path, strategy="fedadam", momenta=False)
    cfg.fl.n_total_clients = 2
    cfg.fl.n_clients_per_round = 2
    cfg.fl.n_rounds = n_rounds
    cfg.fl.eval_interval_rounds = 0  # retrace discipline is about run_round
    cfg.photon.comm_stack.collective_replica = 2
    cfg.photon.comm_stack.collective_quantization = quantization
    cfg.photon.comm_stack.collective_q8_block = 64
    cfg.photon.comm_stack.collective_device_optimizer = True
    cfg.photon.save_path = str(tmp_path / f"plane-{quantization}")
    cfg.validate()
    return cfg


@pytest.mark.parametrize("quantization", ["off", "q8"])
def test_collective_round_e2e_retrace_free_from_round_2(tmp_path, quantization):
    """Acceptance: the full collective-round e2e (real ClientRuntime fits →
    hierarchical exchange → fused device FedAdam) is compile-free from
    round 2 under the PR 6 RetraceSentinel for both quantization policies.
    Also pins the new per-round KPIs and the device-path param flow."""
    from photon_tpu.analysis.runtime import (
        install_retrace_sentinel,
        uninstall_retrace_sentinel,
    )
    from photon_tpu.federation.collective_round import CollectiveFedRunner
    from photon_tpu.parallel.collective_agg import modeled_cross_slice_bytes

    cfg = _plane_cfg(tmp_path, quantization)
    sentinel = install_retrace_sentinel()
    try:
        runner = CollectiveFedRunner(cfg, [0, 1])
        assert runner.device_plane is not None
        sentinel.mark_steady_after(1)  # round 1 = warmup (fit + program compiles)
        for rnd in range(1, cfg.fl.n_rounds + 1):
            metrics = runner.run_round(rnd)
        sentinel.check("collective/e2e")
    finally:
        uninstall_retrace_sentinel()

    # KPI surface: hierarchy stage timings + modeled DCN bytes every round
    hist = runner.history
    for name in (
        "server/collective_agg_time",
        "server/collective_stack_time",
        "server/collective_exchange_time",
        "server/collective_update_time",
        "server/collective_wire_bytes",
    ):
        assert len(hist.series(name)) == cfg.fl.n_rounds, name
    sizes = [int(np.prod(p.shape)) for p in runner.strategy.current_parameters]
    expect = modeled_cross_slice_bytes(
        sizes, 2, replica=2, quantization=quantization, block=64
    )
    assert metrics["server/collective_wire_bytes"] == float(expect)
    # the device plane's params ARE the strategy's params (broadcast mirror)
    for a, b in zip(runner.strategy.current_parameters,
                    runner.device_plane.params_host()):
        np.testing.assert_array_equal(a, b)
    # adaptive bias-correction counter advanced once per round and is
    # checkpointable through the existing host path
    assert runner.device_plane.t == cfg.fl.n_rounds
    assert "_t" in runner.state_for_checkpoint()


def test_collective_round_device_path_matches_host_path(tmp_path):
    """The fused device-optimizer path and the host-strategy path must
    produce the same parameters for the same config (fp32 tolerance —
    psum average is identical, only the update arithmetic moves)."""
    from photon_tpu.federation.collective_round import CollectiveFedRunner

    cfg_dev = _plane_cfg(tmp_path / "dev", "off", n_rounds=2)
    runner_dev = CollectiveFedRunner(cfg_dev, [0, 1])
    runner_dev.run(2)

    cfg_host = _plane_cfg(tmp_path / "host", "off", n_rounds=2)
    cfg_host.photon.comm_stack.collective_device_optimizer = False
    cfg_host.validate()
    runner_host = CollectiveFedRunner(cfg_host, [0, 1])
    runner_host.run(2)

    assert runner_dev.device_plane is not None
    assert runner_host.device_plane is None
    for a, b in zip(runner_dev.strategy.current_parameters,
                    runner_host.strategy.current_parameters):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("device_opt", [True, False], ids=["device-opt", "host-opt"])
def test_collective_round_q8_momenta_stays_finite(tmp_path, device_opt):
    """Regression: q8 + aggregate_momenta went NaN at round 3 — quantization
    noise turns the exactly-zero pseudo-gradient of idle second-moment
    elements tiny-nonzero, the sign-like adaptive server step then kicks
    them negative, and the next fit sqrt()s them. Both optimizer paths now
    clamp the m2 rows >= 0 on the q8 policy (collective_round._nonneg_rows)."""
    from photon_tpu.federation.collective_round import CollectiveFedRunner
    from photon_tpu.train.param_ops import M2_PREFIX

    cfg = _cfg(tmp_path, strategy="fedadam", momenta=True)
    cfg.fl.n_rounds = 3  # the unclamped run NaNs exactly here
    cfg.fl.eval_interval_rounds = 0
    cfg.photon.comm_stack.collective_replica = 2
    cfg.photon.comm_stack.collective_quantization = "q8"
    cfg.photon.comm_stack.collective_device_optimizer = device_opt
    cfg.photon.save_path = str(tmp_path / "q8-momenta")
    cfg.validate()

    runner = CollectiveFedRunner(cfg, list(range(4)))
    assert runner._nonneg_rows  # momenta payload → m2 rows identified
    for rnd in range(1, cfg.fl.n_rounds + 1):
        metrics = runner.run_round(rnd)
        assert np.isfinite(metrics["server/pseudo_grad_norm"]), rnd
    for name, p in zip(runner.meta.names, runner.strategy.current_parameters):
        assert np.all(np.isfinite(p)), name
        if name.startswith(M2_PREFIX):
            assert float(p.min()) >= 0.0, name


def test_collective_runner_resume_via_load_server_state(tmp_path):
    """Runner-level resume: state_for_checkpoint + control_state_for_checkpoint
    → load_server_state keeps the fused FedAdam run bit-identical with the
    uninterrupted run. As in the driver topology's golden resume test,
    ``reset_optimizer`` keeps client optimizer state round-local; loader
    positions resume via the checkpointed client-state sample counters."""
    from photon_tpu.federation.collective_round import CollectiveFedRunner

    def resume_cfg(name):
        cfg = _plane_cfg(tmp_path / name, "off", n_rounds=3)
        cfg.fl.fit_config = {"reset_optimizer": True}
        return cfg

    cont = CollectiveFedRunner(resume_cfg("cont"), [0, 1])
    for rnd in range(1, 4):
        cont.run_round(rnd)

    part = CollectiveFedRunner(resume_cfg("parta"), [0, 1])
    for rnd in range(1, 3):
        part.run_round(rnd)
    params = [p.copy() for p in part.strategy.current_parameters]
    state = {k: [a.copy() for a in v] for k, v in part.state_for_checkpoint().items()}
    control = part.control_state_for_checkpoint()

    resumed = CollectiveFedRunner(resume_cfg("partb"), [0, 1])
    resumed.load_server_state(params, state, control)
    assert resumed.device_plane.t == 2
    assert resumed.server_steps_cumulative == part.server_steps_cumulative
    resumed.run_round(3)

    for a, b in zip(cont.strategy.current_parameters,
                    resumed.strategy.current_parameters):
        np.testing.assert_array_equal(a, b)
