"""The relay-liveness helper shared by bench.py and scripts/tpu_probe.py.

Passive /proc/net/tcp parsing only — must never dial (dialing can disturb a
live claimant on the single-claim relay; see photon_tpu/utils/relay.py).
"""

import socket
import threading

from photon_tpu.utils.relay import RELAY_PORTS, relay_listening


def test_relay_listening_returns_bool():
    assert relay_listening() in (True, False)


def test_detects_listener_on_relay_port():
    # bind one of the relay ports locally; the passive scan must see it
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        try:
            srv.bind(("127.0.0.1", RELAY_PORTS[0]))
        except OSError:
            # port occupied by a real relay — then the scan must be True
            assert relay_listening()
            return
        srv.listen(1)
        assert relay_listening()
    finally:
        srv.close()


def test_no_false_positive_when_ports_free():
    # guard: only meaningful when no relay (or test listener) is up
    if not relay_listening():
        # scanning twice is stable
        assert relay_listening() is False


def test_port_set_matches_deployed_relay_shape():
    # the deployed relay listens on 12 ports in the 8082-8117 range
    assert len(RELAY_PORTS) == 12
    assert all(8082 <= p <= 8117 for p in RELAY_PORTS)
