"""Speculative decoding through the ragged mixed-step grid (ISSUE 15).

Contract layers:

1. **the generalized grid itself** — a multi-token decode row's
   per-position logits AND live KV bytes are BITWISE what K sequential
   single-token ``mixed_step`` calls produce, across mpt-wpe / mpt-alibi
   / llama-gqa, including a slot mid-prefill riding the same batch (the
   satellite pin: the verify columns run op-for-op the decode einsum,
   and masked gather positions are exactly-zero-probability invisible);
2. **greedy end-to-end bit-exactness** — the speculative engine's token
   streams equal the NON-speculative engine / offline oracle, including
   mixed spec+chunk batches, prefix-cache hits, recycled blocks, EOS
   mid-burst and max_new caps — and equal them even under an adversarial
   drafter (rejected drafts roll back via lengths bookkeeping);
3. **temperature** — seeded streams are reproducible, distribution pinned
   statistically vs the non-speculative sampler (rejection sampling
   preserves the distribution; the sample path legitimately differs);
4. **the throttle** — accept-rate EWMA scales K down and falls back to
   plain decode below the floor (adversarial traffic never drafts
   forever), probes re-engage it;
5. **shape discipline** — warm speculative bursts compile NOTHING under
   the retrace sentinel, and the fully-idle engine resets its live-width
   high-water (the ISSUE 15 satellite) with the sentinel still green
   across the reset.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import Config

from tests._helpers import tiny_llama_config


def _serve_cfg(*, alibi=False, llama=False, n_slots=3, block_size=4,
               max_seq=64, max_new=16, budget=2048, prefix=False,
               spec=True, k=4, accept_floor=0.3, probe_ticks=64,
               draft_budget=64) -> Config:
    if llama:
        cfg = tiny_llama_config(n_kv_heads=2)
    else:
        cfg = Config()
        cfg.model.d_model = 32
        cfg.model.n_layers = 2
        cfg.model.n_heads = 4
        cfg.model.vocab_size = 96
        cfg.model.attn_impl = "xla"
        cfg.model.compute_dtype = "float32"
        cfg.model.alibi = alibi
        cfg.model.learned_pos_emb = not alibi
    cfg.model.max_seq_len = max_seq
    cfg.photon.serve.n_slots = n_slots
    cfg.photon.serve.block_size = block_size
    cfg.photon.serve.max_new_tokens = max_new
    cfg.photon.serve.prefill_token_budget = budget
    cfg.photon.serve.prefix_cache = prefix
    sp = cfg.photon.serve.speculative
    sp.enabled = spec
    sp.k = k
    sp.accept_floor = accept_floor
    sp.probe_ticks = probe_ticks
    sp.draft_budget = draft_budget
    return cfg.validate()


def _offline_greedy(cfg, params, prompt, n):
    from photon_tpu.models.decode import make_cached_generate_fn

    buf = np.zeros((1, len(prompt) + n), np.int32)
    buf[0, : len(prompt)] = prompt
    fn = make_cached_generate_fn(cfg.model, params)
    t, _ = fn.many(jnp.asarray(buf), jnp.asarray([len(prompt)], np.int32), n)
    return [int(x) for x in np.asarray(t)[0, len(prompt):]]


class _FixedDrafter:
    """Deterministic test drafter: pops pre-scripted drafts per slot
    (empty once the script runs out)."""

    def __init__(self, script=None):
        self.script = dict(script or {})  # slot -> list of draft lists
        self.began: dict[int, list[int]] = {}
        self.observed: dict[int, list[int]] = {}

    def begin(self, slot, prompt):
        self.began[slot] = list(prompt)
        self.observed.setdefault(slot, [])

    def observe(self, slot, tokens):
        self.observed[slot].extend(tokens)

    def end(self, slot):
        self.began.pop(slot, None)

    def propose(self, slot, k):
        q = self.script.get(slot)
        return list(q.pop(0))[:k] if q else []


# ---------------------------------------------------------------------------
# 1. the generalized grid: bitwise vs K sequential single-token steps
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["mpt-wpe", "mpt-alibi", "llama-gqa"])
def test_spec_grid_bitexact_vs_sequential_steps(name):
    """The satellite pin, at the cache layer: TWO decode rows each
    carrying 3 tokens through ONE ``mixed_chunk_step(n_spec=4)`` call —
    with a THIRD slot's prompt chunk in the same batch — produce
    per-position logits and live KV bytes bitwise equal to three
    sequential single-token calls (chunk riding the first)."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.cache import (
        BlockAllocator, init_paged_state, install_row, mixed_chunk_step,
    )

    cfg = _serve_cfg(alibi=name == "mpt-alibi", llama=name == "llama-gqa",
                     max_seq=32)
    mc = cfg.model
    params = init_params(mc, seed=4)
    bs = cfg.photon.serve.block_size
    m = -(-mc.max_seq_len // bs)
    B = 3
    rng = np.random.default_rng(1)
    prompts = [list(map(int, rng.integers(1, mc.vocab_size, 6)))
               for _ in range(2)]
    chunk_prompt = list(map(int, rng.integers(1, mc.vocab_size, 5)))

    def fresh():
        alloc = BlockAllocator(B * m)
        pst = init_paged_state(mc, B, B * m, bs, m)
        for slot in range(B):
            ids = alloc.alloc(m)
            row = np.full(m, B * m, np.int32)
            row[:m] = ids
            pst = install_row(pst, jnp.int32(slot), jnp.asarray(row),
                              jnp.int32(0))
        return pst

    def prefill(pst, slot, toks):
        n = len(toks)
        tq = 8
        tk = np.zeros((B, tq), np.int32)
        ps = np.zeros((B, tq), np.int32)
        qv = np.zeros((B, tq), bool)
        eo = np.zeros(B, np.int32)
        tk[slot, :n] = toks
        ps[slot, :n] = np.arange(n)
        qv[slot, :n] = True
        eo[slot] = n - 1
        la = pst.lengths
        la = np.asarray(la).copy()
        la[slot] = n
        lg, pst = mixed_chunk_step(
            params, pst, jnp.asarray(tk), jnp.asarray(ps), jnp.asarray(qv),
            jnp.asarray(eo), jnp.asarray(la), jnp.int32(slot), mc,
            n_ctx=4, has_chunk=True)
        return np.asarray(lg), pst

    def decode_call(pst, lengths, last, *, chunk_seg=None, chunk_pos=0):
        """One classic step: decode cols for slots 0/1 (+ optional chunk
        for slot 2); returns (logits [B, V], new state)."""
        has_chunk = chunk_seg is not None
        tq = 8 if has_chunk else 1
        tk = np.zeros((B, tq), np.int32)
        ps = np.zeros((B, tq), np.int32)
        qv = np.zeros((B, tq), bool)
        eo = np.zeros(B, np.int32)
        la = lengths.copy()
        for s in (0, 1):
            tk[s, 0] = last[s]
            ps[s, 0] = lengths[s]
            qv[s, 0] = True
            la[s] += 1
        if has_chunk:
            cn = len(chunk_seg)
            tk[2, :cn] = chunk_seg
            ps[2, :cn] = np.arange(chunk_pos, chunk_pos + cn)
            qv[2, :cn] = True
            la[2] = chunk_pos + cn
        lg, pst = mixed_chunk_step(
            params, pst, jnp.asarray(tk), jnp.asarray(ps), jnp.asarray(qv),
            jnp.asarray(eo), jnp.asarray(la), jnp.int32(2), mc,
            n_ctx=4, has_chunk=has_chunk)
        return np.asarray(lg), pst, la

    # ---- path A: 3 sequential single-token steps (slot 2 chunks on
    # step 1, then sits idle mid-prefill) ---------------------------------
    pstA = fresh()
    lgs = []
    for s, p in enumerate(prompts):
        lg, pstA = prefill(pstA, s, p)
        lgs.append(lg[s])
    lengths = np.asarray([len(prompts[0]), len(prompts[1]), 0], np.int32)
    last = np.asarray([int(np.argmax(lgs[0])), int(np.argmax(lgs[1])), 0],
                      np.int32)
    seq_logits = []
    chunk1 = chunk_prompt[:3]  # slot 2 mid-prefill: 3 of 5 prompt tokens
    lg, pstA, lengths = decode_call(pstA, lengths, last, chunk_seg=chunk1)
    seq_logits.append(lg)
    last = np.asarray([int(np.argmax(lg[0])), int(np.argmax(lg[1])), 0])
    for _ in range(2):
        lg, pstA, lengths = decode_call(pstA, lengths, last)
        seq_logits.append(lg)
        last = np.asarray([int(np.argmax(lg[0])), int(np.argmax(lg[1])), 0])

    # ---- path B: ONE spec grid step with the same 3 tokens per row ------
    pstB = fresh()
    lgsB = []
    for s, p in enumerate(prompts):
        lg, pstB = prefill(pstB, s, p)
        lgsB.append(lg[s])
    np.testing.assert_array_equal(lgs[0], lgsB[0])
    lengths = np.asarray([len(prompts[0]), len(prompts[1]), 0], np.int32)
    feed = np.zeros((B, 3), np.int32)
    for s in (0, 1):
        feed[s, 0] = int(np.argmax(lgsB[s]))
        feed[s, 1] = int(np.argmax(seq_logits[0][s]))
        feed[s, 2] = int(np.argmax(seq_logits[1][s]))
    n_spec = 4  # pow2 bucket of 3 — includes a PAD column
    tq = 8  # chunk bucket dominates
    tk = np.zeros((B, tq), np.int32)
    ps = np.zeros((B, tq), np.int32)
    qv = np.zeros((B, tq), bool)
    eo = np.zeros(B, np.int32)
    la = lengths.copy()
    for s in (0, 1):
        tk[s, :3] = feed[s]
        ps[s, :3] = lengths[s] + np.arange(3)
        qv[s, :3] = True
        la[s] += 3
    cn = len(chunk1)
    tk[2, :cn] = chunk1
    ps[2, :cn] = np.arange(cn)
    qv[2, :cn] = True
    la[2] = cn
    lgB, pstB = mixed_chunk_step(
        params, pstB, jnp.asarray(tk), jnp.asarray(ps), jnp.asarray(qv),
        jnp.asarray(eo), jnp.asarray(la), jnp.int32(2), mc,
        n_ctx=4, has_chunk=True, n_spec=n_spec)
    lgB = np.asarray(lgB)  # [B, n_spec, V]

    for i in range(3):
        for s in (0, 1):
            np.testing.assert_array_equal(
                seq_logits[i][s], lgB[s, i],
                err_msg=f"{name}: slot {s} verified column {i}")
    # live KV bytes identical (only the trash block may differ — pad
    # columns and idle rows write there)
    ckA, ckB = np.asarray(pstA.cache_k), np.asarray(pstB.cache_k)
    trash = ckA.shape[0] - 1
    np.testing.assert_array_equal(ckA[:trash], ckB[:trash])


@pytest.mark.parametrize("name", ["mpt-wpe", "llama-gqa"])
def test_engine_spec_step_matches_sequential_engine(name):
    """The same pin at the ENGINE layer: spec_step with all-accept drafts
    (+ a mid-prefill batch-mate's chunk in the same call) emits exactly
    the sequential engine's tokens and leaves identical decode state
    (subsequent plain steps continue bitwise-identically)."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    cfg = _serve_cfg(llama=name == "llama-gqa", budget=3)
    params = init_params(cfg.model, seed=4)
    rng = np.random.default_rng(7)
    p0 = list(map(int, rng.integers(1, cfg.model.vocab_size, 5)))
    p1 = list(map(int, rng.integers(1, cfg.model.vocab_size, 7)))
    p2 = list(map(int, rng.integers(1, cfg.model.vocab_size, 6)))

    def boot(engine):
        engine.begin(0, p0, 10)
        engine.begin(1, p1, 10)
        while engine.pending_tokens(0) or engine.pending_tokens(1):
            s = 0 if engine.pending_tokens(0) else 1
            engine.mixed_step((s, engine.pending_tokens(s)),
                              include_decode=False)
        engine.begin(2, p2, 8)  # slot 2 stays mid-prefill during the step

    # sequential reference: chunk + 3 single-token steps
    ref = PagedEngine(cfg, params)
    boot(ref)
    ref_toks = {0: [], 1: []}
    out, em = ref.mixed_step((2, 3))  # chunk rides step 1
    for s in (0, 1):
        ref_toks[s].append(int(out[s]))
    for _ in range(2):
        out, em = ref.mixed_step(None)
        for s in (0, 1):
            ref_toks[s].append(int(out[s]))

    # speculative: ONE step whose drafts are the (known-good) refs
    eng = PagedEngine(cfg, params)
    boot(eng)
    drafts = {s: ref_toks[s][:2] for s in (0, 1)}
    out2, n_em = eng.spec_step((2, 3), drafts)
    for s in (0, 1):
        assert int(n_em[s]) == 3  # 2 accepted drafts + the bonus
        assert [int(x) for x in out2[s, :3]] == ref_toks[s]
    assert eng.pending_tokens(2) == len(p2) - 3  # the chunk advanced too
    np.testing.assert_array_equal(eng._lengths[:2], ref._lengths[:2])
    # continued PLAIN decode stays identical: the accepted drafts' KV is
    # bitwise the sequential path's
    for _ in range(3):
        a, _ = ref.mixed_step(None)
        b, _ = eng.mixed_step(None)
        np.testing.assert_array_equal(a[:2], b[:2])


def test_spec_rejection_rolls_back_and_stays_bitexact():
    """An ADVERSARIAL drafter (garbage drafts every step) must cost
    nothing but wasted verify columns: the emitted greedy stream still
    equals the oracle, rejected positions roll back (lengths advance by
    exactly the accepted count), and later steps overwrite the stale
    bytes invisibly."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    cfg = _serve_cfg(n_slots=2)
    params = init_params(cfg.model, seed=4)
    p = [5, 9, 2, 7]
    want = _offline_greedy(cfg, params, p, 8)
    eng = PagedEngine(cfg, params)
    eng.begin(0, p, 8)
    while eng.pending_tokens(0):
        eng.mixed_step((0, eng.pending_tokens(0)), include_decode=False)
    got = []
    while len(got) < 8:
        bad = [(want[len(got)] + 1) % cfg.model.vocab_size] * 3  # never match
        out, n_em = eng.spec_step(None, {0: bad})
        n = int(n_em[0])
        assert n == 1  # first draft rejected → bonus token only
        assert int(eng._lengths[0]) == len(p) + len(got) + 1  # rolled back
        got.extend(int(x) for x in out[0, :n])
    assert got == want


# ---------------------------------------------------------------------------
# 2. greedy end-to-end bit-exactness through the batcher
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["mpt-wpe", "mpt-alibi", "llama-gqa"])
def test_spec_serving_bitexact_with_offline(name):
    """Acceptance pin: the speculative batcher (n-gram drafter, chunked
    prefill budget 3, prefix cache ON, recycled blocks) completes every
    greedy request EXACTLY like the offline oracle."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(alibi=name == "mpt-alibi", llama=name == "llama-gqa",
                     prefix=True, budget=3)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(
        engine, max_queue=16, prefill_token_budget=3,
        speculative=cfg.photon.serve.speculative,
    ).start()
    rng = np.random.default_rng(5)
    shared = list(map(int, rng.integers(1, cfg.model.vocab_size, 8)))
    try:
        for i in range(6):
            suf = list(map(int, rng.integers(1, cfg.model.vocab_size,
                                             int(rng.integers(1, 6)))))
            p = (shared + suf) if i % 2 else suf
            got = batcher.submit(p, 12).result(timeout=120)
            assert got == _offline_greedy(cfg, params, p, 12), p
        assert batcher._spec.drafted > 0  # drafting genuinely happened
        assert batcher._spec.accepted > 0
        assert engine.n_active == 0
    finally:
        batcher.close()


def test_spec_eos_and_max_new_mid_burst():
    """EOS landing INSIDE an emission burst truncates the stream exactly
    like the non-speculative engine (the burst's tail is discarded), and
    max_new_tokens is never exceeded."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg()
    params = init_params(cfg.model, seed=4)
    p = [3, 3, 8, 1]
    ref = _offline_greedy(cfg, params, p, 12)
    eos = ref[4]  # truncate mid-stream; the cycle guarantee: it recurs
    want = ref[: ref.index(eos) + 1]
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(
        engine, max_queue=4, speculative=cfg.photon.serve.speculative,
    ).start()
    try:
        got = batcher.submit(p, 12, eos_id=eos).result(timeout=120)
        assert got == want
        got2 = batcher.submit(p, 5, eos_id=-1).result(timeout=120)
        assert got2 == ref[:5]  # max_new cap honored mid-burst
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# 3. temperature: determinism + distribution
# ---------------------------------------------------------------------------


def test_spec_temperature_reproducible_and_distribution_pinned():
    """Seeded temperature streams under speculation are REPRODUCIBLE
    (same seed + same traffic → same completion), and the per-position
    sampling distribution matches the non-speculative sampler
    statistically: rejection sampling against the drafter's point-mass
    proposal preserves the model's distribution exactly, so the FIRST
    sampled token's histogram over many seeds must agree between the
    speculative and non-speculative engines."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    cfg = _serve_cfg(n_slots=1, max_new=8)
    params = init_params(cfg.model, seed=4)
    p = [5, 9, 2, 7]
    eng = PagedEngine(cfg, params)  # ONE engine: jit caches shared

    def run(spec_drafts, seed, n=4, temp=0.8):
        eng.begin(0, p, 8, temperature=temp, seed=seed)
        while eng.pending_tokens(0):
            eng.mixed_step((0, eng.pending_tokens(0)), include_decode=False)
        toks = [int(eng._last[0])]
        while len(toks) < n:
            if spec_drafts:
                out, n_em = eng.spec_step(None, {0: [toks[-1]] * 2})
                toks.extend(int(x) for x in out[0, : int(n_em[0])])
            else:
                out, _ = eng.mixed_step(None)
                toks.append(int(out[0]))
        eng.evict(0)
        return toks[:n]

    # reproducibility: identical runs → identical streams
    assert run(True, seed=11) == run(True, seed=11)
    assert run(False, seed=11) == run(False, seed=11)
    # the prefill emission is drawn BEFORE any draft is tested → bitwise
    # the non-speculative sampler's token, per seed
    for s in range(12):
        assert run(True, seed=s, n=1) == run(False, seed=s, n=1)


def test_nondrafting_temp_row_is_batchmate_independent():
    """A seeded temperature row that carries NO drafts must emit the
    SAME stream whether its step ran as the classic program (alone) or
    as a speculative grid (a greedy batch-mate drafted) — the verify
    loop keeps the classic split(k)-per-emission chain, so batch-mates'
    draft schedules can never perturb a non-drafting row."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    cfg = _serve_cfg(n_slots=2, max_new=16)
    params = init_params(cfg.model, seed=4)
    p_temp, p_greedy = [5, 9, 2, 7], [3, 3, 8, 1]

    def boot(eng, with_mate):
        eng.begin(0, p_temp, 12, temperature=0.8, seed=17)
        while eng.pending_tokens(0):
            eng.mixed_step((0, eng.pending_tokens(0)), include_decode=False)
        if with_mate:
            eng.begin(1, p_greedy, 12)
            while eng.pending_tokens(1):
                eng.mixed_step((1, eng.pending_tokens(1)),
                               include_decode=False)

    # alone: classic n_spec == 1 steps
    a = PagedEngine(cfg, params)
    boot(a, with_mate=False)
    alone = [int(a._last[0])]
    for _ in range(6):
        out, _ = a.mixed_step(None)
        alone.append(int(out[0]))

    # with a drafting batch-mate: every step is a speculative grid, but
    # slot 0 itself never drafts
    b = PagedEngine(cfg, params)
    boot(b, with_mate=True)
    mate_drafts = [int(b._last[1])] * 3  # content irrelevant — slot 1's
    together = [int(b._last[0])]
    while len(together) < 7:
        out, n_em = b.spec_step(None, {1: list(mate_drafts)})
        together.extend(int(x) for x in out[0, : int(n_em[0])])
    assert together[:7] == alone


def test_spec_temperature_rejection_distribution():
    """The rejection-sampling identity itself, pinned directly on
    _verify_rows: with a point-mass proposal at draft d, P(emit = t)
    must equal the model's softmax p(t) — accept contributes p(d) at d,
    the residual contributes p(t) elsewhere. Empirical over many keys on
    a fixed 4-token distribution."""
    from photon_tpu.serve.engine import _verify_rows

    n = 4000  # one BATCHED _verify_rows call: 4000 independent rows
    logits = jnp.broadcast_to(
        jnp.log(jnp.asarray([0.5, 0.25, 0.15, 0.10], jnp.float32)), (n, 4)
    )
    grid = jnp.stack([logits, logits], axis=1)  # [n, 2, V]
    tokens = jnp.broadcast_to(jnp.asarray([7, 0], jnp.int32), (n, 2))
    temps = jnp.ones(n, jnp.float32)
    emit = jnp.ones(n, bool)
    n_valid = jnp.full(n, 2, jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n, dtype=jnp.uint32))
    out, n_em, _ = _verify_rows(grid, tokens, temps, keys, emit, n_valid, 2)
    first = np.asarray(out)[:, 0]
    freq = np.bincount(first, minlength=4)[:4] / n
    np.testing.assert_allclose(freq, [0.5, 0.25, 0.15, 0.10], atol=0.03)


# ---------------------------------------------------------------------------
# 4. the drafter + throttle
# ---------------------------------------------------------------------------


def test_ngram_drafter_prompt_lookup_and_cycles():
    from photon_tpu.serve.draft import NGramDrafter

    d = NGramDrafter(max_ngram=3, min_ngram=1)
    d.begin(0, [1, 2, 3, 4, 1, 2, 3])
    # trailing [1,2,3] matched at the prompt head → continuation 4, then
    # self-extension continues the match: 4,1,2,3 → ...
    assert d.propose(0, 4) == [4, 1, 2, 3]
    d.observe(0, [9])
    assert d.propose(0, 2) == []  # ...3,9 never seen: nothing to propose
    d.observe(0, [9, 9])
    # a period-1 cycle still yields a FULL-depth draft (self-extension)
    assert d.propose(0, 4) == [9, 9, 9, 9]
    d.end(0)
    assert d.propose(0, 4) == []  # ended slots propose nothing


def test_spec_controller_throttle_and_probe():
    from photon_tpu.serve.draft import SpecController

    c = SpecController(k_max=4, accept_floor=0.3, ewma_alpha=0.5,
                       probe_ticks=3)
    assert c.next_k() == 4  # optimistic start
    c.observe(4, 4)
    assert c.k_effective() == 4
    c.observe(4, 2)  # ewma 1.0 → 0.75
    assert c.next_k() == 3  # proportional throttle
    for _ in range(6):
        c.observe(4, 0)
    assert c.ewma < 0.3
    assert c.k_effective() == 0  # pure read: below floor = plain decode
    assert c.next_k() == 0  # ticks 1, 2 ...
    assert c.next_k() == 0
    assert c.next_k() == 1  # tick 3: the probe
    assert c.next_k() == 0  # probe clock reset
    # a run of accepted probes climbs back over the floor
    for _ in range(4):
        c.observe(1, 1)
    assert c.k_effective() >= 1
    # stats read k_effective without advancing the probe clock
    c2 = SpecController(k_max=2, accept_floor=0.9, probe_ticks=2)
    c2.observe(10, 0)
    for _ in range(10):
        assert c2.k_effective() == 0  # pure — no probe ever fires here
    assert c2.next_k() == 0
    assert c2.next_k() == 1


def test_adversarial_traffic_auto_throttles_to_plain_decode():
    """Incompressible traffic (garbage drafts rejected every step) drives
    the EWMA under the floor: drafting stops (spec_k 0), the engine runs
    the CLASSIC compiled step again, and completions stay oracle-exact
    throughout."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher
    from photon_tpu.utils.profiling import SERVE_SPEC_K

    cfg = _serve_cfg(probe_ticks=0)  # once off, stays off
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    # a drafter whose guesses are ALWAYS wrong: propose vocab-shifted
    # copies of the last emission
    vocab = cfg.model.vocab_size

    class BadDrafter(_FixedDrafter):
        def __init__(self):
            super().__init__()
            self.last: dict[int, int] = {}

        def observe(self, slot, tokens):
            self.last[slot] = tokens[-1]

        def propose(self, slot, k):
            t = self.last.get(slot, 1)
            return [(t + 17 + i) % vocab or 1 for i in range(k)]

    batcher = ContinuousBatcher(
        engine, max_queue=8, speculative=cfg.photon.serve.speculative,
        drafter=BadDrafter(),
    ).start()
    rng = np.random.default_rng(3)
    try:
        for i in range(3):
            p = list(map(int, rng.integers(1, vocab, 5)))
            got = batcher.submit(p, 12).result(timeout=120)
            assert got == _offline_greedy(cfg, params, p, 12)
        st = batcher.stats()
        assert st[SERVE_SPEC_K] == 0.0  # throttled off
        assert batcher._spec.ewma < 0.3
        # drafting really stopped: a fresh request moves drafted no more
        before = batcher._spec.drafted
        p = list(map(int, rng.integers(1, vocab, 5)))
        assert batcher.submit(p, 8).result(timeout=120) \
            == _offline_greedy(cfg, params, p, 8)
        assert batcher._spec.drafted == before
    finally:
        batcher.close()


def test_spec_moe_silently_ineligible():
    """MoE: batch-global expert capacity breaks per-row purity — the
    batcher quietly serves plain decode (the prefix-cache precedent)."""
    from photon_tpu.serve.scheduler import ContinuousBatcher

    class _McEng:
        class mc:
            mlp = "moe"

    cfg = _serve_cfg()
    b = ContinuousBatcher(_McEng(), speculative=cfg.photon.serve.speculative)
    assert b._spec is None and b._drafter is None


# ---------------------------------------------------------------------------
# 5. config validation + KPI registry
# ---------------------------------------------------------------------------


def test_speculative_config_validation():
    for field, bad in (("k", 0), ("k", 33), ("draft_budget", 0),
                       ("min_ngram", 0), ("max_ngram", 0),
                       ("accept_floor", 1.5), ("ewma_alpha", 0.0),
                       ("probe_ticks", -1)):
        cfg = _serve_cfg()
        setattr(cfg.photon.serve.speculative, field, bad)
        with pytest.raises(ValueError, match="speculative"):
            cfg.validate()
    cfg = _serve_cfg()
    cfg.photon.serve.speculative.min_ngram = 2
    cfg.photon.serve.speculative.max_ngram = 1  # min > max
    with pytest.raises(ValueError, match="speculative"):
        cfg.validate()


def test_spec_kpis_registered_and_recorded():
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher
    from photon_tpu.utils.profiling import (
        SERVE_SPEC_ACCEPT_RATE,
        SERVE_SPEC_ACCEPTED,
        SERVE_SPEC_DRAFTED,
        SERVE_SPEC_K,
        SERVE_SPEC_STEPS,
        registered_metric_names,
    )

    names = registered_metric_names()
    for n in (SERVE_SPEC_DRAFTED, SERVE_SPEC_ACCEPTED, SERVE_SPEC_STEPS,
              SERVE_SPEC_ACCEPT_RATE, SERVE_SPEC_K):
        assert n in names
    cfg = _serve_cfg()
    params = init_params(cfg.model, seed=4)
    batcher = ContinuousBatcher(
        PagedEngine(cfg, params), max_queue=4,
        speculative=cfg.photon.serve.speculative,
    ).start()
    try:
        batcher.submit([5, 9, 2], 8).result(timeout=120)
        st = batcher.stats()
        assert st[SERVE_SPEC_DRAFTED] >= st[SERVE_SPEC_ACCEPTED] >= 0
        assert 0.0 <= st[SERVE_SPEC_ACCEPT_RATE] <= 1.0
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# 6. shape discipline: the sentinel over spec bursts + the idle reset
# ---------------------------------------------------------------------------


def test_ctx_width_resets_when_fully_idle():
    """The ISSUE 15 satellite: one long request must not inflate every
    later batch's attention width for the daemon's lifetime — a fully
    idle engine drops the high-water back to 1 (mid-flight it stays
    monotone), and the compiled-width cache makes the re-warm free."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    cfg = _serve_cfg(n_slots=2, max_seq=64, spec=False)
    params = init_params(cfg.model, seed=4)
    eng = PagedEngine(cfg, params)
    long_p = list(range(1, 41))  # 40 tokens → 10+ blocks → width 16
    eng.begin(0, long_p, 8)
    while eng.pending_tokens(0):
        eng.mixed_step((0, eng.pending_tokens(0)), include_decode=False)
    assert eng.attn_stats()["ctx_blocks"] >= 16
    eng.begin(1, [1, 2, 3], 4)  # short batch-mate pays the wide walk...
    while eng.pending_tokens(1):
        eng.mixed_step((1, eng.pending_tokens(1)), include_decode=False)
    eng.evict(0)
    assert eng.attn_stats()["ctx_blocks"] >= 16  # ...monotone while live
    eng.evict(1)
    assert eng.attn_stats()["ctx_blocks"] == 1.0  # fully idle: reset
    eng.begin(0, [4, 5, 6], 4)  # 7 tokens = 2 blocks: runs at width 2,
    while eng.pending_tokens(0):  # not the dead giant's 16
        eng.mixed_step((0, eng.pending_tokens(0)), include_decode=False)
    assert eng.attn_stats()["ctx_blocks"] == 2.0


def test_retrace_sentinel_green_spec_bursts_and_idle_reset():
    """Warm speculative bursts — every (chunk, n_spec, live-width) bucket
    compiled — then a guarded burst AND a full-idle high-water reset AND
    a re-warmed burst compile NOTHING. Driven synchronously (this test
    owns the driver phases) so the step sequence is deterministic."""
    from photon_tpu.analysis import runtime as lint_rt
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=2, max_seq=32, accept_floor=0.0, budget=4)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(
        engine, max_queue=8, prefill_token_budget=4,
        speculative=cfg.photon.serve.speculative,
    )
    # warm every pow2 verify width a k=4 drafter can mint (n_spec 2/4/8
    # with the bonus column; 1 is the classic step) at every ctx width
    # the bursts below will touch
    def burst():
        reqs = [batcher.submit([7, 3, 7, 3, 7, 3], 10),
                batcher.submit([2, 8, 2, 8, 2], 8)]
        while not all(r.finished for r in reqs):
            batcher._admit_phase()
            batcher._step_phase()
        return reqs

    def warm_spec_widths():
        engine.begin(0, [1, 2, 3], 4)
        while engine.pending_tokens(0):
            engine.mixed_step((0, engine.pending_tokens(0)),
                              include_decode=False)
        for d in ([5], [5, 6, 7], [5, 6, 7, 1, 2, 3, 4]):
            engine.spec_step(None, {0: list(d)})
        engine.evict(0)

    warm_spec_widths()
    burst()
    burst()  # second pass: post-reset traffic re-hits warmed buckets
    with lint_rt.retrace_guard(steady=True) as sentinel:
        burst()
        assert engine.n_active == 0  # burst drained → high-water reset
        assert engine.attn_stats()["ctx_blocks"] == 1.0
        burst()  # the re-warm after the reset compiles nothing
    assert sentinel.violations == []
    assert batcher._spec.drafted > 0
    batcher.close()
