"""Pallas flash-attention kernel parity in INTERPRET mode (CPU-executable).

Until now the kernel only ever executed on the real chip (bench parity);
interpret mode runs the same kernel logic through the Pallas interpreter so
fwd/bwd numerics — including the new in-kernel ALiBi bias and the lse ring
path — are validated in every CPU test run. Oracle: ``xla_attention`` /
``xla_chunk_attention``. On-chip parity (real Mosaic lowering) remains
covered by ``bench.py --kernel-parity`` (KERNEL_PARITY.json).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.ops.attention import xla_attention
from photon_tpu.ops.flash_attention import flash_attention, flash_attention_with_lse
from photon_tpu.ops.ring_attention import xla_chunk_attention

B, S, H, D = 2, 256, 4, 64
BLOCK = 128


def _qkv(d=D, s=S, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(key, (B, s, H, d), dtype) for key in ks)


def _rel(a, ref):
    a = np.asarray(a, np.float32)
    ref = np.asarray(ref, np.float32)
    return float(np.linalg.norm(a - ref) / (np.linalg.norm(ref) + 1e-12))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("alibi", [False, True])
def test_forward_parity(causal, alibi):
    q, k, v = _qkv()
    o_k = flash_attention(q, k, v, causal=causal, alibi=alibi,
                          block_q=BLOCK, block_k=BLOCK, interpret=True)
    o_x = xla_attention(q, k, v, causal=causal, alibi=alibi)
    assert _rel(o_k, o_x) < 2e-5, (causal, alibi)


@pytest.mark.parametrize("alibi", [False, True])
def test_backward_parity(alibi):
    q, k, v = _qkv()
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

    def loss(fn):
        return jax.grad(
            lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum(), argnums=(0, 1, 2)
        )

    gk = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, alibi=alibi, block_q=BLOCK, block_k=BLOCK, interpret=True
    ))(q, k, v)
    gx = loss(lambda q, k, v: xla_attention(q, k, v, causal=True, alibi=alibi))(q, k, v)
    for name, a, ref in zip(("dq", "dk", "dv"), gk, gx):
        assert _rel(a, ref) < 5e-5, (name, alibi)


def test_lane_padded_d_head():
    """d_head 80 < 128: zero-pad path must not perturb outputs."""
    q, k, v = _qkv(d=80)
    o_k = flash_attention(q, k, v, causal=True, block_q=BLOCK, block_k=BLOCK, interpret=True)
    o_x = xla_attention(q, k, v, causal=True)
    assert _rel(o_k, o_x) < 2e-5


def test_d_head_128_1b_shape():
    q, k, v = _qkv(d=128)
    o_k = flash_attention(q, k, v, causal=True, block_q=BLOCK, block_k=BLOCK, interpret=True)
    o_x = xla_attention(q, k, v, causal=True)
    assert _rel(o_k, o_x) < 2e-5


def test_bf16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    o_k = flash_attention(q, k, v, causal=True, block_q=BLOCK, block_k=BLOCK, interpret=True)
    o_x = xla_attention(q, k, v, causal=True)
    assert _rel(o_k, o_x) < 2e-2  # bf16 tolerance


def test_lse_path_parity():
    """The ring inner kernel: (o, lse) vs the XLA chunk oracle off-diagonal."""
    q, k, v = _qkv(s=128)
    o_k, lse_k = flash_attention_with_lse(
        q, k, v, causal=True, q_start=128, k_start=0,
        block_q=BLOCK, block_k=BLOCK, interpret=True,
    )
    o_x, lse_x = xla_chunk_attention(q, k, v, q_start=128, k_start=0, causal=True)
    assert _rel(o_k, o_x) < 2e-5
    assert _rel(lse_k, lse_x) < 2e-5


def test_alibi_long_range_decay():
    """Behavioral: with ALiBi, attention to distant keys decays — the last
    query's effective context is shorter than without ALiBi."""
    q, k, v = _qkv(seed=3)
    o_plain = flash_attention(q, k, v, causal=True, block_q=BLOCK, block_k=BLOCK, interpret=True)
    o_alibi = flash_attention(q, k, v, causal=True, alibi=True,
                              block_q=BLOCK, block_k=BLOCK, interpret=True)
    # must actually differ (bias applied), and both be finite
    assert _rel(o_alibi, o_plain) > 1e-3
    assert np.isfinite(np.asarray(o_alibi)).all()


@pytest.mark.parametrize("block", [256, 512])
def test_large_tile_parity(block):
    """The 512-tile configuration the bench's block trial runs on hardware
    (PERF.md lever 2, ``PHOTON_BENCH_TRY_BLOCK``) must be numerically
    correct BEFORE its first on-chip execution — fwd + bwd at a sequence
    long enough (1024) that multiple 512 tiles and the causal off-diagonal
    both exercise."""
    q, k, v = _qkv(s=1024, seed=7)
    o_k = flash_attention(q, k, v, causal=True, block_q=block, block_k=block,
                          interpret=True)
    o_x = xla_attention(q, k, v, causal=True)
    assert _rel(o_k, o_x) < 2e-5, block

    w = jax.random.normal(jax.random.PRNGKey(8), o_x.shape)

    def loss(fn):
        return jax.grad(
            lambda q, k, v: (fn(q, k, v).astype(jnp.float32) * w).sum(),
            argnums=(0, 1, 2),
        )

    gk = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=block, block_k=block, interpret=True
    ))(q, k, v)
    gx = loss(lambda q, k, v: xla_attention(q, k, v, causal=True))(q, k, v)
    for a, ref in zip(gk, gx):
        assert _rel(a, ref) < 2e-4, block


@pytest.mark.parametrize("group", [2, 4])
@pytest.mark.parametrize("alibi", [False, True])
def test_gqa_native_parity(group, alibi):
    """Grouped-query attention runs NATIVELY in the kernel (k/v at h_kv
    width, q heads index-mapped onto kv group rows — no repeated-kv tensor;
    the dkv backward accumulates each kv row over its whole q-head group).
    Oracle: kv replicated to full width + the XLA path; jax.grad through
    the replication sums group members, so dk/dv shapes and values must
    match the kernel's kv-row-major outputs exactly."""
    q, _, _ = _qkv(s=256)
    h_kv = H // group
    ks = jax.random.split(jax.random.PRNGKey(11), 2)
    k = jax.random.normal(ks[0], (B, 256, h_kv, D))
    v = jax.random.normal(ks[1], (B, 256, h_kv, D))

    def rep(x):
        return jnp.repeat(x, group, axis=2)

    o_k = flash_attention(q, k, v, causal=True, alibi=alibi,
                          block_q=128, block_k=128, interpret=True)
    o_x = xla_attention(q, rep(k), rep(v), causal=True, alibi=alibi)
    assert _rel(o_k, o_x) < 2e-5

    w = jax.random.normal(jax.random.PRNGKey(12), o_x.shape)
    gk = jax.grad(
        lambda q, k, v: (flash_attention(
            q, k, v, causal=True, alibi=alibi, block_q=128, block_k=128,
            interpret=True) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gx = jax.grad(
        lambda q, k, v: (xla_attention(
            q, rep(k), rep(v), causal=True, alibi=alibi) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, ref in zip(gk, gx):
        assert a.shape == ref.shape
        assert _rel(a, ref) < 2e-4


def test_lse_path_gqa_parity():
    """The ring inner kernel with grouped kv (_flash_lse h_q plumbing):
    (o, lse) forward AND gradients through BOTH outputs vs the
    replicated-kv chunk oracle — covers the _flash_lse_bwd group-reshape
    recompute, which no TPU is needed to regress."""
    group = 2
    q, _, _ = _qkv(s=128)
    h_kv = H // group
    ks = jax.random.split(jax.random.PRNGKey(21), 2)
    k = jax.random.normal(ks[0], (B, 128, h_kv, D))
    v = jax.random.normal(ks[1], (B, 128, h_kv, D))

    def rep(x):
        return jnp.repeat(x, group, axis=2)

    o_k, lse_k = flash_attention_with_lse(
        q, k, v, causal=True, q_start=128, k_start=0,
        block_q=BLOCK, block_k=BLOCK, interpret=True,
    )
    o_x, lse_x = xla_chunk_attention(q, rep(k), rep(v), q_start=128, k_start=0,
                                     causal=True)
    assert _rel(o_k, o_x) < 2e-5
    assert _rel(lse_k, lse_x) < 2e-5

    wo = jax.random.normal(jax.random.PRNGKey(22), o_x.shape)
    wl = jax.random.normal(jax.random.PRNGKey(23), lse_x.shape)

    def loss_kernel(q, k, v):
        o, lse = flash_attention_with_lse(
            q, k, v, causal=True, q_start=128, k_start=0,
            block_q=BLOCK, block_k=BLOCK, interpret=True)
        return (o * wo).sum() + (lse * wl).sum()

    def loss_ref(q, k, v):
        o, lse = xla_chunk_attention(q, rep(k), rep(v), q_start=128, k_start=0,
                                     causal=True)
        return (o * wo).sum() + (lse * wl).sum()

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, ref in zip(gk, gx):
        assert a.shape == ref.shape
        assert _rel(a, ref) < 2e-4
