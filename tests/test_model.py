import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.config.schema import ModelConfig
from photon_tpu.models.mpt import MPTModel, init_params

TINY = ModelConfig(
    name="tiny",
    d_model=64,
    n_layers=2,
    n_heads=4,
    max_seq_len=64,
    vocab_size=128,
    attn_impl="xla",
    compute_dtype="float32",
)


def test_forward_shapes_and_dtype():
    params = init_params(TINY, seed=0)
    model = MPTModel(TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, TINY.vocab_size)
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_params_stacked_layers():
    params = init_params(TINY, seed=0)
    kernel = params["blocks"]["block"]["wqkv"]["kernel"]
    assert kernel.shape == (TINY.n_layers, TINY.d_model, 3 * TINY.d_model)


def test_causality():
    """Changing a future token must not change past logits."""
    params = init_params(TINY, seed=0)
    model = MPTModel(TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, TINY.vocab_size)
    logits1 = model.apply({"params": params}, tokens)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % TINY.vocab_size)
    logits2 = model.apply({"params": params}, tokens2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(logits1[0, 10]), np.asarray(logits2[0, 10]))


def test_bf16_compute_dtype_runs():
    cfg = ModelConfig(**{**TINY.__dict__, "compute_dtype": "bfloat16"})
    params = init_params(cfg, seed=0)
    model = MPTModel(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = model.apply({"params": params}, tokens)
    assert logits.dtype == jnp.float32  # logits cast back to fp32
    # params stay fp32 masters
    assert params["wte"]["embedding"].dtype == jnp.float32


def test_remat_matches_no_remat():
    cfg_r = ModelConfig(**{**TINY.__dict__, "remat": True})
    params = init_params(TINY, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 0, TINY.vocab_size)
    out_a = MPTModel(TINY).apply({"params": params}, tokens)
    out_b = MPTModel(cfg_r).apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=1e-5, atol=1e-5)


def test_125m_param_count():
    cfg = ModelConfig()  # defaults are the 125m shape
    params = jax.eval_shape(lambda: init_params(cfg, seed=0))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    # ~124M with tied embeddings (wte 50368*768 + wpe 2048*768 + 12 blocks)
    assert 1.1e8 < n < 1.4e8
