"""Pipeline parallelism (``parallel/pipeline.py``): the GPipe-style stage
schedule over the ``pipe`` mesh axis must produce the SAME loss and
gradients as the non-pipelined grad-accumulation step — pipelining is an
execution schedule, not a numerical change. No reference analog (the
reference's in-client parallelism is DDP/FSDP/TP via Composer,
``trainer_utils.py:1640-1720``); equivalence is checked against this
repo's own ``make_train_step``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding

# the pipeline's partial-manual shard_map (manual over `pipe` only) needs
# the jax.shard_map era of partial-manual lowering; the older
# experimental-shard_map + auto-axes spelling hits an XLA "PartitionId is
# not supported for SPMD partitioning" abort on EVERY pipe mesh. Equivalence
# tests only run where the capability exists; validation tests always run.
_PARTIAL_MANUAL = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="partial-manual shard_map unsupported on this jax/XLA",
)

from photon_tpu.config.schema import Config, MeshConfig
from photon_tpu.models.mpt import MPTModel, init_params
from photon_tpu.parallel.mesh import make_mesh
from photon_tpu.parallel.pipeline import make_pipeline_train_step
from photon_tpu.parallel.sharding import batch_spec, state_shardings
from photon_tpu.train.train_step import (
    init_train_state,
    make_loss_fn,
    make_train_step,
)


def _cfg(mesh: MeshConfig, **model_overrides) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 4
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    for k, v in model_overrides.items():
        setattr(cfg.model, k, v)
    cfg.mesh = mesh
    cfg.train.global_batch_size = 8
    cfg.train.device_microbatch_size = 2
    return cfg.validate()


def _pipeline_grads(cfg, params, tokens, n_micro, chunk):
    """One sgd(lr=1) pipeline step: params_before - params_after == grads."""
    model = MPTModel(cfg.model)
    mesh = make_mesh(cfg.mesh)
    tx = optax.sgd(1.0)
    state = init_train_state(model, tx, params)
    sh = state_shardings(state, mesh)
    state = jax.tree.map(lambda l, s: jax.device_put(l, s), state, sh)
    bs = NamedSharding(mesh, batch_spec(mesh))
    step = jax.jit(
        make_pipeline_train_step(
            model, tx, mesh, n_microbatches=n_micro, loss_chunk_tokens=chunk
        ),
        in_shardings=(sh, bs), out_shardings=(sh, None),
    )
    new_state, metrics = step(state, jax.device_put(tokens, bs))
    grads = jax.tree.map(
        lambda a, b: jnp.asarray(a) - b, params, jax.device_get(new_state.params)
    )
    return grads, float(metrics["loss"])


def _reference_grads(cfg, params, tokens, n_micro, chunk):
    model = MPTModel(cfg.model)
    lf = make_loss_fn(model, chunk)

    def loss(p):
        m = tokens.reshape(n_micro, tokens.shape[0] // n_micro, tokens.shape[1])
        return sum(lf(p, m[i]) for i in range(n_micro)) / n_micro

    return jax.grad(loss)(params), float(loss(params))


@_PARTIAL_MANUAL
@pytest.mark.parametrize(
    "mesh,chunk",
    [
        (MeshConfig(data=2, pipe=4), 2048),  # pipe x data, chunked CE
        (MeshConfig(pipe=2, fsdp=2), 2048),  # pipe x fsdp (auto inside)
        (MeshConfig(tensor=2, pipe=2), 2048),  # pipe x tensor (TP inside stages)
        (MeshConfig(data=2, pipe=4), 0),     # unchunked tail path
    ],
)
def test_pipeline_matches_reference_grads(mesh, chunk):
    cfg = _cfg(mesh)
    params = init_params(cfg.model, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    g_pipe, loss_pipe = _pipeline_grads(cfg, params, tokens, 2, chunk)
    g_ref, loss_ref = _reference_grads(cfg, params, tokens, 2, chunk)
    assert abs(loss_pipe - loss_ref) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5), g_pipe, g_ref
    )


@_PARTIAL_MANUAL
def test_pipeline_matches_with_remat_and_llama_family():
    """Remat inside stages + the llama knobs (RoPE/RMSNorm/SwiGLU/GQA)
    flow through MPTBlock reuse unchanged."""
    cfg = _cfg(
        MeshConfig(data=2, pipe=2),
        remat=True, rope=True, norm="rmsnorm", mlp="swiglu",
        n_kv_heads=1, tie_embeddings=False, learned_pos_emb=False,
    )
    params = init_params(cfg.model, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    g_pipe, loss_pipe = _pipeline_grads(cfg, params, tokens, 4, 2048)
    g_ref, loss_ref = _reference_grads(cfg, params, tokens, 4, 2048)
    assert abs(loss_pipe - loss_ref) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5), g_pipe, g_ref
    )


@_PARTIAL_MANUAL
def test_pipeline_matches_with_moe():
    """MoE stages through the pipeline: the per-layer Switch aux losses
    are collected through the stage scan (bubble ticks excluded) and the
    total objective matches the non-pipelined MoE step."""
    cfg = _cfg(
        MeshConfig(pipe=2, expert=2),
        mlp="moe", moe_num_experts=4, moe_top_k=2,
    )
    params = init_params(cfg.model, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 64)
    g_pipe, loss_pipe = _pipeline_grads(cfg, params, tokens, 2, 2048)
    g_ref, loss_ref = _reference_grads(cfg, params, tokens, 2, 2048)
    assert abs(loss_pipe - loss_ref) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5), g_pipe, g_ref
    )


def test_pipeline_validation():
    with pytest.raises(ValueError, match="divide evenly"):
        _cfg(MeshConfig(pipe=3))  # 4 layers % 3 stages
    with pytest.raises(ValueError, match="sequence"):
        _cfg(MeshConfig(pipe=2, sequence=2))
    with pytest.raises(ValueError, match="ONE batch-sharded axis"):
        # compound (data, fsdp) batch sharding under manual pipe trips an
        # XLA SPMD partitioner CHECK failure — rejected at validation
        _cfg(MeshConfig(data=2, fsdp=2, pipe=2))
    with pytest.raises(ValueError, match="ONE batch-sharded axis"):
        # expert is a batch axis too (batch_spec)
        _cfg(MeshConfig(data=2, expert=2, pipe=2),
             mlp="moe", moe_num_experts=4)
    # pallas under pipe is legal at validation time and NOT mutated: a
    # config serialized after validate() must match the operator's input.
    # The xla fallback happens at Trainer construction (next test).
    cfg = _cfg(MeshConfig(pipe=2), attn_impl="pallas")
    assert cfg.model.attn_impl == "pallas"


def test_trainer_defers_pallas_pipe_fallback():
    """The pallas→xla fallback under pipe>1 lives at step construction:
    the Trainer's model runs xla attention inside stages while the config
    of record keeps the operator's attn_impl."""
    from photon_tpu.train.trainer import Trainer

    cfg = _cfg(MeshConfig(data=2, pipe=2), attn_impl="pallas")
    with pytest.warns(UserWarning, match="falling back to"):
        trainer = Trainer(cfg, mesh=make_mesh(cfg.mesh), init_seed=0)
    assert cfg.model.attn_impl == "pallas"  # untouched config of record
    assert trainer.model.cfg.attn_impl == "xla"


@_PARTIAL_MANUAL
def test_trainer_runs_pipelined():
    """Trainer picks the pipeline step for pipe>1 meshes; loss falls on a
    repeated batch and the state layout (checkpoint format) is unchanged."""
    from photon_tpu.train.trainer import Trainer

    cfg = _cfg(MeshConfig(data=2, pipe=2))
    trainer = Trainer(cfg, mesh=make_mesh(cfg.mesh), init_seed=0)
    tokens = np.random.default_rng(0).integers(0, 64, (8, 16), dtype=np.int32)
    losses = []
    for _ in range(8):
        trainer.state, m = trainer._train_step(trainer.state, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
