"""Regression tests for the round-loop failure-path bugs found in rounds 1-2
(ADVICE.md r1 findings a-d + the sliding-window timeout):

a. ``TcpServerDriver.send`` to a dead/unknown node must synthesize a failure
   reply, not KeyError-crash the round loop the failure budget exists to
   survive.
b. centralized mid-run eval must fire at its configured interval even when
   save_every doesn't divide eval_interval.
c. ``evaluate_round`` failures must respect ``ignore_failed_rounds``.
d. eval rounds must score the SAME window of the val stream every time.
e. ``recv_any`` TimeoutError inside the sliding window counts against the
   failure budget instead of killing the server loop.
"""

import types
from collections import deque

import pytest

from photon_tpu.federation import ServerApp, TooManyFailuresError
from photon_tpu.federation.driver import Driver
from photon_tpu.federation.messages import Ack
from photon_tpu.federation.tcp import TcpServerDriver
from tests.test_federation import make_app, make_cfg


# ---------------------------------------------------------------------------
# a. dead-node send
# ---------------------------------------------------------------------------


def test_tcp_send_to_unknown_node_synthesizes_failure():
    """Sending to a node that died (already dropped from the registry, e.g.
    its id still sits in the sliding window's free list) must not raise."""
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=0)
    try:
        mid = driver.send("ghost", {"kind": "fit"})  # never registered
        nid, got_mid, reply = driver.recv_any(timeout=5)
        assert (nid, got_mid) == ("ghost", mid)
        assert isinstance(reply, Ack) and not reply.ok and "died" in reply.detail
    finally:
        driver.shutdown()


# ---------------------------------------------------------------------------
# window-level fakes
# ---------------------------------------------------------------------------


class ScriptedDriver(Driver):
    """Minimal driver: ``behavior(nid) -> "ok" | "die"`` decides each reply."""

    def __init__(self, nodes: dict[str, str]) -> None:
        self.behavior = dict(nodes)  # nid -> "ok" | "die"
        self.alive = set(nodes)
        self.sends: list[tuple[str, object]] = []
        self._replies: deque[tuple[str, int, object]] = deque()
        self._mid = iter(range(10**6))

    def node_ids(self):
        return sorted(self.alive)

    def send(self, node_id, msg):
        mid = next(self._mid)
        self.sends.append((node_id, msg))
        if node_id not in self.alive or self.behavior[node_id] == "die":
            self.alive.discard(node_id)
            self._replies.append(
                (node_id, mid, Ack(ok=False, detail="node died", node_id=node_id))
            )
        else:
            cid = msg[1][0] if isinstance(msg, tuple) else -1
            self._replies.append(
                (node_id, mid, types.SimpleNamespace(error=None, cid=cid))
            )
        return mid

    def recv_any(self, timeout=None):
        if not self._replies:
            raise TimeoutError("scripted: nothing pending")
        return self._replies.popleft()

    def broadcast(self, msg):
        return {nid: Ack(ok=True) for nid in self.alive}

    def shutdown(self):
        pass


class StalledDriver(ScriptedDriver):
    """Accepts sends but never replies: every recv_any times out."""

    def send(self, node_id, msg):
        self.sends.append((node_id, msg))
        return next(self._mid)

    def recv_any(self, timeout=None):
        raise TimeoutError("stalled")


def _window_app(tmp_path, driver, **fl_kw):
    cfg = make_cfg(tmp_path, **fl_kw)
    from photon_tpu.federation import ParamTransport

    return ServerApp(cfg, driver, ParamTransport("inline"))


def test_sliding_window_drops_dead_node_and_retries_elsewhere(tmp_path):
    driver = ScriptedDriver({"n0": "ok", "n1": "die"})
    app = _window_app(tmp_path, driver, accept_failures_cnt=0)
    make_ins = lambda cids: ("fit", cids)  # noqa: E731
    got = list(app._sliding_window(1, [0, 1], make_ins, timeout=5.0))
    # both cids eventually succeed (the one that hit n1 retried on n0)
    assert sorted(r.cid for r in got) == [0, 1]
    # n1 died on its first task and was dropped from rotation: exactly 1 send
    assert sum(1 for nid, _ in driver.sends if nid == "n1") == 1


def test_sliding_window_all_nodes_dead_respects_budget(tmp_path):
    driver = ScriptedDriver({"n0": "die"})
    app = _window_app(tmp_path, driver, accept_failures_cnt=0)
    make_ins = lambda cids: ("fit", cids)  # noqa: E731
    with pytest.raises(TooManyFailuresError):
        list(app._sliding_window(1, [0, 1, 2], make_ins, timeout=5.0))
    # generous budget: the same situation is absorbed
    app2 = _window_app(tmp_path, ScriptedDriver({"n0": "die"}), accept_failures_cnt=8)
    assert list(app2._sliding_window(1, [0, 1, 2], make_ins, timeout=5.0)) == []


def test_sliding_window_timeout_counts_against_budget(tmp_path):
    """recv_any TimeoutError must convert to budgeted failures, not escape."""
    driver = StalledDriver({"n0": "ok"})
    app = _window_app(tmp_path, driver, accept_failures_cnt=0)
    make_ins = lambda cids: ("fit", cids)  # noqa: E731
    with pytest.raises(TooManyFailuresError) as ei:
        list(app._sliding_window(1, [0, 1], make_ins, timeout=0.01))
    assert "timeout" in str(ei.value) or "no live nodes" in str(ei.value)

    app2 = _window_app(tmp_path, StalledDriver({"n0": "ok"}), accept_failures_cnt=8)
    assert list(app2._sliding_window(1, [0, 1], make_ins, timeout=0.01)) == []


# ---------------------------------------------------------------------------
# c. evaluate_round under ignore_failed_rounds
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_eval_round_failure_respects_ignore_failed_rounds(tmp_path, monkeypatch):
    cfg = make_cfg(
        tmp_path, n_rounds=1, eval_interval_rounds=1, ignore_failed_rounds=True
    )
    app = make_app(cfg, tmp_path)

    def boom(server_round):
        raise TooManyFailuresError("eval blew the budget")

    monkeypatch.setattr(app, "evaluate_round", boom)
    history = app.run()  # must NOT raise
    assert history.latest("server/eval_round_failed") == 1.0
    # the fit round itself still aggregated
    assert history.latest("server/round_time") is not None
    app.driver.shutdown()


def test_eval_round_failure_raises_without_ignore(tmp_path, monkeypatch):
    cfg = make_cfg(
        tmp_path, n_rounds=1, eval_interval_rounds=1, ignore_failed_rounds=False
    )
    app = make_app(cfg, tmp_path)

    def boom(server_round):
        raise TooManyFailuresError("eval blew the budget")

    monkeypatch.setattr(app, "evaluate_round", boom)
    with pytest.raises(TooManyFailuresError):
        app.run()
    app.driver.shutdown()


# ---------------------------------------------------------------------------
# b. centralized eval interval alignment
# ---------------------------------------------------------------------------


def test_centralized_eval_fires_at_configured_interval(tmp_path):
    from photon_tpu.centralized import run_centralized

    cfg = make_cfg(tmp_path)
    cfg.photon.checkpoint = True
    # save_every=5 does NOT divide eval_interval=3: before the fix, mid-run
    # eval never fired because steps only stopped at save boundaries
    history = run_centralized(
        cfg, total_steps=6, eval_interval_steps=3, checkpoint_interval_steps=5
    )
    eval_steps = [s for s, _ in history.series("eval/loss")]
    assert 3 in eval_steps, f"mid-run eval missing: {eval_steps}"
    assert 6 in eval_steps  # final eval


# ---------------------------------------------------------------------------
# d. eval rounds score a fixed window
# ---------------------------------------------------------------------------


def test_eval_scores_identical_window_every_round(tmp_path):
    from photon_tpu.federation import ParamTransport
    from photon_tpu.federation.client_runtime import ClientRuntime
    from photon_tpu.federation.messages import EvaluateIns

    cfg = make_cfg(tmp_path)
    rt = ClientRuntime(cfg, ParamTransport("inline"))
    from photon_tpu.codec import params_to_ndarrays

    meta, arrays = params_to_ndarrays(rt.trainer.state.params)
    ptr = rt.transport.put("init", meta, arrays)
    rt.set_broadcast_params(ptr)

    ins = EvaluateIns(server_round=1, cids=[0], params=None, max_batches=2)
    r1 = rt.evaluate(ins, cid=0)
    r2 = rt.evaluate(ins, cid=0)
    assert r1.error is None and r2.error is None
    # same params + same fixed eval window => bit-identical loss
    assert r1.loss == r2.loss
    rt.close()


def test_stale_reply_params_are_freed(tmp_path):
    """A FitRes that arrives after its cid was written off (e.g. post-timeout)
    must have its transport segment freed, not leaked."""

    class StaleReplyDriver(ScriptedDriver):
        def __init__(self):
            super().__init__({"n0": "ok"})
            self._injected = False

        def recv_any(self, timeout=None):
            if not self._injected:
                self._injected = True
                return (
                    "n0",
                    999_999,  # correlation id nobody is waiting for
                    types.SimpleNamespace(error=None, cid=7, params="stale-ptr"),
                )
            return super().recv_any(timeout)

    driver = StaleReplyDriver()
    app = _window_app(tmp_path, driver, accept_failures_cnt=0)
    freed = []
    app.transport.free = lambda ptr: freed.append(ptr)
    make_ins = lambda cids: ("fit", cids)  # noqa: E731
    got = list(app._sliding_window(1, [0], make_ins, timeout=5.0))
    assert sorted(r.cid for r in got) == [0]
    assert freed == ["stale-ptr"]
