"""Native lib parity tests: ctypes bindings vs numpy/zlib oracles.
Skipped when the lib isn't built (`make native`)."""

import zlib

import numpy as np
import pytest

from photon_tpu import native


requires_native = pytest.mark.skipif(not native.available(), reason="make native not built")


@requires_native
def test_gather_widen_u16():
    rng = np.random.default_rng(0)
    rows = [rng.integers(0, 1 << 16, 32, dtype=np.uint16) for _ in range(17)]
    out = np.empty((17, 32), np.int32)
    native.gather_rows(rows, out)
    for i, r in enumerate(rows):
        np.testing.assert_array_equal(out[i], r.astype(np.int32))


@requires_native
def test_gather_widen_u32():
    rng = np.random.default_rng(1)
    rows = [rng.integers(0, 1 << 18, 16, dtype=np.uint32) for _ in range(5)]
    out = np.empty((5, 16), np.int32)
    native.gather_rows(rows, out)
    for i, r in enumerate(rows):
        np.testing.assert_array_equal(out[i], r.astype(np.int32))


@requires_native
def test_par_memcpy_large():
    rng = np.random.default_rng(2)
    src = rng.integers(0, 255, 40 << 20, dtype=np.uint8)  # crosses thread threshold
    dst = np.zeros_like(src)
    native.parallel_memcpy(dst, src)
    np.testing.assert_array_equal(dst, src)


@requires_native
def test_crc32_matches_zlib():
    rng = np.random.default_rng(3)
    data = rng.integers(0, 255, 100_000, dtype=np.uint8).tobytes()
    assert native.crc32(data) == zlib.crc32(data)
    assert native.crc32(data, seed=123) == zlib.crc32(data, 123)


def test_fallback_paths_work(monkeypatch):
    """With the lib hidden, every binding must fall back to numpy/zlib."""
    monkeypatch.setattr(native, "_LIB", False)
    rows = [np.arange(8, dtype=np.uint16), np.arange(8, 16, dtype=np.uint16)]
    out = np.empty((2, 8), np.int32)
    native.gather_rows(rows, out)
    np.testing.assert_array_equal(out[1], np.arange(8, 16))
    src = np.arange(100, dtype=np.uint8)
    dst = np.zeros_like(src)
    native.parallel_memcpy(dst, src)
    np.testing.assert_array_equal(dst, src)
    assert native.crc32(src.tobytes()) == zlib.crc32(src.tobytes())
