"""Golden-value tests for aggregation + server optimizers (SURVEY.md §7.5:
"golden-value unit tests against hand-computed rounds")."""

import numpy as np
import pytest

from photon_tpu.config.schema import FLConfig
from photon_tpu.strategy import (
    ClientResult,
    FedAdam,
    FedAvgEff,
    FedMom,
    FedNesterov,
    FedYogi,
    aggregate_inplace,
    dispatch_strategy,
    weighted_loss_avg,
)
from photon_tpu.strategy.metrics import GradientNoiseScale


def arrs(*vals):
    return [np.full((2, 2), v, np.float32) for v in vals]


def test_aggregate_inplace_weighted_mean():
    results = [(arrs(1.0), 1), (arrs(4.0), 3)]
    avg, n = aggregate_inplace(iter(results))
    assert n == 4
    np.testing.assert_allclose(avg[0], np.full((2, 2), (1 * 1 + 4 * 3) / 4), rtol=1e-6)


def test_aggregate_inplace_matches_direct_mean_many():
    rng = np.random.default_rng(0)
    payloads = [([rng.normal(size=(3, 5)).astype(np.float32)], int(n)) for n in rng.integers(1, 100, 12)]
    avg, n_tot = aggregate_inplace(iter(payloads))
    direct = sum(a[0].astype(np.float64) * n for a, n in payloads) / sum(n for _, n in payloads)
    np.testing.assert_allclose(avg[0], direct, rtol=1e-5)


def test_aggregate_rejects_empty_and_bad_counts():
    with pytest.raises(ValueError):
        aggregate_inplace(iter([]))
    with pytest.raises(ValueError):
        aggregate_inplace(iter([(arrs(1.0), 0)]))


def _round(strategy, client_vals, server_val=1.0, n_samples=None, rnd=1):
    strategy.initialize(arrs(server_val)) if strategy.current_parameters is None else None
    n_samples = n_samples or [1] * len(client_vals)
    results = (
        ClientResult(cid=i, arrays=arrs(v), n_samples=n)
        for i, (v, n) in enumerate(zip(client_vals, n_samples))
    )
    params, metrics = strategy.aggregate_fit(rnd, results)
    return params[0][0, 0], metrics


def test_fedavg_lr1_is_plain_average():
    s = FedAvgEff(server_learning_rate=1.0)
    val, _ = _round(s, [0.0, 2.0])  # avg=1.0, g = 1-1 = 0 → x=1... use server 4
    s2 = FedAvgEff(server_learning_rate=1.0)
    s2.initialize(arrs(4.0))
    val, _ = _round(s2, [0.0, 2.0])
    # g = 4 - 1 = 3; x = 4 - 3 = 1 = the average
    np.testing.assert_allclose(val, 1.0, rtol=1e-6)


def test_fedavg_halved_lr():
    s = FedAvgEff(server_learning_rate=0.5)
    s.initialize(arrs(4.0))
    val, _ = _round(s, [0.0, 2.0])
    np.testing.assert_allclose(val, 4.0 - 0.5 * 3.0, rtol=1e-6)  # 2.5


def test_client_count_scaling():
    s = FedAvgEff(server_learning_rate=0.1, client_count_scaling="linear")
    assert s.effective_lr(4) == pytest.approx(0.4)
    s2 = FedAvgEff(server_learning_rate=0.1, client_count_scaling="sqrt")
    assert s2.effective_lr(4) == pytest.approx(0.2)


def test_nesterov_two_rounds_golden():
    # μ=0.5, η=1. Round1: avg=0 from x=1 → g=1; m=0.5*0+1=1; step=g+μm=1.5; x=-0.5
    # Round2: clients at -0.5 → g = x - avg = 0 → m=0.5; step=0+0.25... compute:
    s = FedNesterov(server_learning_rate=1.0, server_momentum=0.5)
    s.initialize(arrs(1.0))
    v1, _ = _round(s, [0.0, 0.0], rnd=1)
    np.testing.assert_allclose(v1, -0.5, rtol=1e-6)
    # round 2: clients return -1.5 (avg), g = -0.5 - (-1.5) = 1.0
    v2, _ = _round(s, [-1.5, -1.5], rnd=2)
    # m = 0.5*1 + 1 = 1.5; step = 1 + 0.5*1.5 = 1.75; x = -0.5 - 1.75 = -2.25
    np.testing.assert_allclose(v2, -2.25, rtol=1e-6)


def test_fedmom_golden():
    s = FedMom(server_learning_rate=1.0, server_momentum=0.9)
    s.initialize(arrs(1.0))
    v1, _ = _round(s, [0.0], rnd=1)  # g=1, m=1, x = 0
    np.testing.assert_allclose(v1, 0.0, atol=1e-7)
    v2, _ = _round(s, [-1.0], rnd=2)  # g = 0-(-1)=1; m=0.9+1=1.9; x=0-1.9
    np.testing.assert_allclose(v2, -1.9, rtol=1e-6)


def test_fedadam_first_step_golden():
    # t=1: m=(1-b1)g /(1-b1) = g; v=(1-b2)g²/(1-b2)=g²; x -= lr·g/(|g|+tau) = sign
    s = FedAdam(server_learning_rate=0.1, server_beta_1=0.9, server_beta_2=0.99, server_tau=0.0)
    s.initialize(arrs(1.0))
    v1, _ = _round(s, [0.5], rnd=1)  # g=0.5 → step = 0.1 * 0.5/0.5 = 0.1
    np.testing.assert_allclose(v1, 0.9, rtol=1e-6)


def test_fedyogi_second_moment_sign():
    s = FedYogi(server_learning_rate=0.1, server_beta_1=0.0, server_beta_2=0.99, server_tau=0.0)
    s.initialize(arrs(1.0))
    # v starts 0; g²>0 ⇒ sign(0-g²)=-1 ⇒ v = (1-b2)·g², same as adam's first step
    v1, _ = _round(s, [0.5], rnd=1)
    np.testing.assert_allclose(v1, 0.9, rtol=1e-6)


def test_adaptive_state_checkpoint_roundtrip():
    s = FedAdam(server_learning_rate=0.1)
    s.initialize(arrs(1.0))
    _round(s, [0.5], rnd=1)
    ckpt_state = s.state_for_checkpoint()
    ckpt_params = [a.copy() for a in s.current_parameters]

    s2 = FedAdam(server_learning_rate=0.1)
    s2.initialize(ckpt_params, ckpt_state)
    assert s2._t == 1
    v_a, _ = _round(s, [0.2], rnd=2)
    v_b, _ = _round(s2, [0.2], rnd=2)
    np.testing.assert_allclose(v_a, v_b, rtol=1e-6)


def test_dispatcher_covers_all():
    for name in ("fedavg", "nesterov", "fedmom", "fedadam", "fedyogi"):
        s = dispatch_strategy(FLConfig(strategy_name=name))
        assert s.name == name


def test_weighted_loss_avg():
    assert weighted_loss_avg([(1, 2.0), (3, 4.0)]) == pytest.approx((2 + 12) / 4)


def test_weighted_average_metrics_ragged_exact():
    """Single-pass rewrite (ISSUE 2 satellite): exact values pinned on a
    ragged metrics dict — every key normalizes by the samples of the
    clients that REPORTED it, not the round total."""
    from photon_tpu.strategy import weighted_average_metrics

    results = [
        (2, {"loss": 4.0, "acc": 0.5}),
        (6, {"loss": 2.0}),                 # no "acc"
        (4, {"acc": 1.0, "extra": 7.0}),    # no "loss"
        (0, {"ghost": 3.0}),                # zero-weight: must not divide by 0
    ]
    out = weighted_average_metrics(results)
    assert out == {
        "loss": pytest.approx((2 * 4.0 + 6 * 2.0) / 8),   # 2.5 over 8 samples
        "acc": pytest.approx((2 * 0.5 + 4 * 1.0) / 6),    # 5/6 over 6 samples
        "extra": pytest.approx(7.0),
    }
    assert "ghost" not in out
    assert weighted_average_metrics([]) == {}


def test_metrics_weighted_and_telemetry():
    s = FedAvgEff(server_learning_rate=1.0)
    s.initialize(arrs(1.0))
    results = (
        ClientResult(cid=i, arrays=arrs(v), n_samples=n, metrics={"loss": loss})
        for i, (v, n, loss) in enumerate([(0.0, 1, 2.0), (2.0, 3, 4.0)])
    )
    _, metrics = s.aggregate_fit(1, results)
    assert metrics["loss"] == pytest.approx(3.5)
    assert metrics["server/n_clients"] == 2
    assert "server/pseudo_grad_norm" in metrics


def test_gradient_noise_scale_uniform_grads():
    """Identical client grads ⇒ zero noise ⇒ S≈0."""
    gns = GradientNoiseScale(ema_alpha=0.0)
    out = gns.update([4.0, 4.0], [10, 10], aggregate_sq_norm=4.0, total_samples=20)
    assert out["server/gns_trace_est"] == pytest.approx(0.0, abs=1e-9)
    assert out["server/gradient_noise_scale"] == pytest.approx(0.0, abs=1e-9)


def test_gradient_noise_scale_positive():
    gns = GradientNoiseScale(ema_alpha=0.0)
    # small-batch norms larger than big-batch ⇒ positive noise scale
    out = gns.update([5.0, 5.0], [10, 10], aggregate_sq_norm=3.0, total_samples=20)
    assert out["server/gradient_noise_scale"] > 0


# ---------------------------------------------------------------------------
# round-3 golden additions: weighted, two distinct layers, all five
# strategies against fully hand-computed values
# ---------------------------------------------------------------------------


def _two_layer_round(strategy, server=(4.0, -2.0)):
    """One round, 2 clients with unequal weights, 2 distinct layers.

    clients: c0 = (1, -1) with n=1;  c1 = (5, 3) with n=3
    weighted avg = (1*1+5*3)/4 , (-1*1+3*3)/4 = (4.0, 2.0)
    pseudo-grad g = x - avg = (0.0, -4.0)
    """
    strategy.initialize([np.full((2,), v, np.float32) for v in server])
    results = (
        ClientResult(
            cid=i,
            arrays=[np.full((2,), a, np.float32), np.full((2,), b, np.float32)],
            n_samples=n,
        )
        for i, (a, b, n) in enumerate([(1.0, -1.0, 1), (5.0, 3.0, 3)])
    )
    params, _ = strategy.aggregate_fit(1, results)
    return params[0][0], params[1][0]


def test_golden_weighted_fedavg_two_layers():
    s = FedAvgEff(server_learning_rate=0.5)
    l0, l1 = _two_layer_round(s)
    # x - 0.5*g: 4 - 0 = 4 ; -2 - 0.5*(-4) = 0
    np.testing.assert_allclose((l0, l1), (4.0, 0.0), rtol=1e-6)


def test_golden_weighted_nesterov_two_layers():
    s = FedNesterov(server_learning_rate=1.0, server_momentum=0.5)
    l0, l1 = _two_layer_round(s)
    # m = 0.5*0 + g = g; step = g + 0.5*g = 1.5g: (0, -6); x - step = (4, 4)
    np.testing.assert_allclose((l0, l1), (4.0, 4.0), rtol=1e-6)


def test_golden_weighted_fedmom_two_layers():
    s = FedMom(server_learning_rate=1.0, server_momentum=0.9)
    l0, l1 = _two_layer_round(s)
    # m = g; x - m = (4-0, -2-(-4)) = (4, 2)
    np.testing.assert_allclose((l0, l1), (4.0, 2.0), rtol=1e-6)


def test_golden_weighted_fedadam_two_layers():
    # t=1 bias correction cancels: m̂=g, v̂=g²; step = 0.1·g/(|g|+τ) ≈ 0.1·sign(g)
    # (τ>0 keeps the g=0 layer at exactly 0/τ = 0)
    s = FedAdam(server_learning_rate=0.1, server_beta_1=0.9, server_beta_2=0.99, server_tau=1e-9)
    l0, l1 = _two_layer_round(s)
    np.testing.assert_allclose(l0, 4.0, atol=1e-6)        # g=0: no movement
    np.testing.assert_allclose(l1, -2.0 + 0.1, rtol=1e-5)  # DESCENT: -η·sign(g)= +0.1
    # the sign decision (divergence note in strategy/optimizers.py): the step
    # moves TOWARD the client average (avg=2 > x=-2), unlike the reference's +g


def test_golden_weighted_fedyogi_two_layers():
    s = FedYogi(server_learning_rate=0.1, server_beta_1=0.9, server_beta_2=0.99, server_tau=1e-9)
    l0, l1 = _two_layer_round(s)
    # first step: v=(1-b2)g²·sign(g²-0)=(1-b2)g² == adam's first step
    np.testing.assert_allclose(l0, 4.0, atol=1e-6)
    np.testing.assert_allclose(l1, -2.0 + 0.1, rtol=1e-5)


def test_adaptive_descends_toward_client_average():
    """The sign decision, behaviorally: repeated rounds with clients pinned at
    avg=2 must move the server params toward 2, not away (the reference's
    ``x + η·…`` on ``g = x − avg`` walks away; see strategy/optimizers.py)."""
    for cls in (FedAdam, FedYogi):
        s = cls(server_learning_rate=0.5, server_tau=1e-9)
        s.initialize(arrs(-2.0))
        dist0 = abs(-2.0 - 2.0)
        v = -2.0
        for rnd in range(1, 6):
            v, _ = _round(s, [2.0, 2.0], rnd=rnd)
        assert abs(v - 2.0) < dist0, f"{cls.__name__} moved away from the client average"
