"""Eval CLI + centralized warm start: params flow from a centralized run into
federated init and into the standalone evaluator."""

import json

import numpy as np
import pytest

from photon_tpu.centralized import run_centralized
from photon_tpu.checkpoint import FileStore
from photon_tpu.federation.server import centralized_warm_start
from tests.test_centralized import _cfg


@pytest.fixture(scope="module")
def central_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("warm")
    cfg = _cfg(tmp)
    run_centralized(cfg, total_steps=2, dump_params=True)
    return cfg, tmp


def test_warm_start_loads_latest_central_params(central_run):
    cfg, tmp = central_run
    store = FileStore(tmp / "save" / "store")
    meta, params = centralized_warm_start(store, cfg.run_uuid)
    assert len(params) == len(meta.names)
    assert all(np.isfinite(p).all() for p in params)
    with pytest.raises(FileNotFoundError):
        centralized_warm_start(store, "no-such-run")


def test_eval_cli_npz_and_icl(central_run, tmp_path, capsys):
    cfg, tmp = central_run
    rows = [{"query": "abc", "choices": ["d", "z"], "gold": 0}] * 2
    task_file = tmp_path / "toy.jsonl"
    task_file.write_text("\n".join(json.dumps(r) for r in rows))

    cfg_yaml = tmp_path / "cfg.yaml"
    cfg.to_yaml(cfg_yaml)

    from photon_tpu.eval.__main__ import main

    main([
        "--params-npz", str(tmp / "save" / "params_final.npz"),
        "--config", str(cfg_yaml),
        "--dataset", "",  # skip val loss (no client_* layout in central save)
        "--icl-tasks", str(task_file),
        "--tokenizer", "byte-fallback",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "icl/toy/accuracy" in out
    assert 0.0 <= out["icl/toy/accuracy"] <= 1.0


def test_federated_init_from_run(central_run):
    """photon.init_from_run warm-starts the federated globals from the
    centralized checkpoint (reference: init_utils.py:43-125)."""
    cfg, tmp = central_run
    from photon_tpu.federated import build_app

    fed_cfg = _cfg(tmp)  # same save_path → same store
    fed_cfg.photon.checkpoint = False
    fed_cfg.photon.init_from_run = cfg.run_uuid
    fed_cfg.fl.n_total_clients = 2
    fed_cfg.fl.n_clients_per_round = 2
    app = build_app(fed_cfg)
    try:
        store = FileStore(tmp / "save" / "store")
        meta, params = centralized_warm_start(store, cfg.run_uuid)
        assert app.metadata.names == meta.names
        for a, b in zip(app.strategy.current_parameters, params):
            np.testing.assert_array_equal(a, b)
    finally:
        app.driver.shutdown()


def test_eval_cli_store_round_source(central_run, tmp_path, capsys):
    """--store/--run without --round loads the centralized checkpoint."""
    cfg, tmp = central_run
    cfg_yaml = tmp_path / "cfg.yaml"
    cfg.to_yaml(cfg_yaml)
    from photon_tpu.eval.__main__ import main

    main([
        "--store", str(tmp / "save" / "store"),
        "--run", cfg.run_uuid,
        "--config", str(cfg_yaml),
    ])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert out == "{}"  # no dataset/icl requested; params load path exercised
