"""Validated per-round FitRoundConfig / EvaluateRoundConfig (reference:
pydantic FitConfig/EvaluateConfig with ast validators,
``photon/clients/configs.py:55-214``): typo'd knobs fail loudly."""

import pytest

from photon_tpu.federation.configs import (
    ConfigError,
    EvaluateRoundConfig,
    FitRoundConfig,
)


def test_defaults():
    c = FitRoundConfig.from_dict(None)
    assert c.reset_optimizer is False
    assert c.personalize_patterns == []
    assert c.loader_state is None
    e = EvaluateRoundConfig.from_dict({})
    assert e.use_unigram_metrics is True


def test_typo_key_raises():
    # the exact bug class VERDICT r2 called out: 'reset_optimzer' no-ops
    with pytest.raises(ConfigError, match="reset_optimzer"):
        FitRoundConfig.from_dict({"reset_optimzer": True})
    with pytest.raises(ConfigError, match="unknown"):
        EvaluateRoundConfig.from_dict({"use_unigrams": True})


def test_type_validation():
    with pytest.raises(ConfigError, match="expected bool"):
        FitRoundConfig.from_dict({"reset_optimizer": 1})
    with pytest.raises(ConfigError, match="list"):
        FitRoundConfig.from_dict({"personalize_patterns": "not-a-list"})
    with pytest.raises(ConfigError, match="dict"):
        FitRoundConfig.from_dict({"loader_state": [1, 2]})


def test_string_encoded_values_parse():
    """Configs may travel as strings (reference: ast.literal_eval validators)."""
    c = FitRoundConfig.from_dict(
        {"reset_optimizer": "True", "randomize_patterns": "['blocks/.*wqkv']"}
    )
    assert c.reset_optimizer is True
    assert c.randomize_patterns == ["blocks/.*wqkv"]
    with pytest.raises(ConfigError, match="unparseable"):
        FitRoundConfig.from_dict({"reset_optimizer": "tru"})


def test_fit_with_typo_knob_fails_loudly(tmp_path):
    """End to end: a typo'd knob in FitIns.config produces an error FitRes
    (counted by the failure budget), not a silent no-op."""
    from photon_tpu.federation import ParamTransport
    from photon_tpu.federation.client_runtime import ClientRuntime
    from photon_tpu.federation.messages import FitIns
    from tests.test_federation import make_cfg

    cfg = make_cfg(tmp_path)
    rt = ClientRuntime(cfg, ParamTransport("inline"))
    from photon_tpu.codec import params_to_ndarrays

    meta, arrays = params_to_ndarrays(rt.trainer.state.params)
    rt.set_broadcast_params(rt.transport.put("init", meta, arrays))
    res = rt.fit(
        FitIns(
            server_round=1, cids=[0], params=None, local_steps=1,
            server_steps_cumulative=0, config={"reset_optimzer": True},
        ),
        cid=0,
    )
    assert res.error is not None and "reset_optimzer" in res.error
    rt.close()


def test_server_rejects_bad_fit_config(tmp_path):
    from photon_tpu.federation import ParamTransport, ServerApp
    from tests.test_federation import make_cfg
    from photon_tpu.federation.driver import Driver

    class NullDriver(Driver):
        def node_ids(self):
            return []

        def send(self, node_id, msg):
            return 0

        def recv_any(self, timeout=None):
            raise TimeoutError

        def shutdown(self):
            pass

    cfg = make_cfg(tmp_path)
    cfg.fl.fit_config = {"client_checkpoint": True}  # missing trailing 's'
    with pytest.raises(ConfigError, match="client_checkpoint"):
        ServerApp(cfg, NullDriver(), ParamTransport("inline"))


def test_server_rejects_bad_eval_config(tmp_path):
    from photon_tpu.federation import ParamTransport, ServerApp
    from tests.test_federation import make_cfg
    from photon_tpu.federation.driver import Driver

    class NullDriver(Driver):
        def node_ids(self):
            return []

        def send(self, node_id, msg):
            return 0

        def recv_any(self, timeout=None):
            raise TimeoutError

        def shutdown(self):
            pass

    cfg = make_cfg(tmp_path)
    cfg.fl.eval_config = {"use_unigram_metrcs": True}  # typo'd
    with pytest.raises(ConfigError, match="use_unigram_metrcs"):
        ServerApp(cfg, NullDriver(), ParamTransport("inline"))


@pytest.mark.slow
def test_eval_config_reaches_clients(tmp_path):
    """eval_config set in FLConfig must arrive in EvaluateIns.config."""
    from photon_tpu.federation.messages import EvaluateIns
    from tests.test_federation import make_app, make_cfg

    cfg = make_cfg(tmp_path, eval_interval_rounds=1)
    cfg.fl.eval_config = {"use_unigram_metrics": False}
    app = make_app(cfg, tmp_path)
    seen = []
    orig_send = app.driver.send

    def spy_send(nid, msg):
        if isinstance(msg, EvaluateIns):
            seen.append(msg.config)
        return orig_send(nid, msg)

    app.driver.send = spy_send
    app.run(n_rounds=1)
    assert seen and all(c == {"use_unigram_metrics": False} for c in seen)
    app.driver.shutdown()
