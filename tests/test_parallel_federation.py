"""MoE and pipeline models through the FULL federation stack: the new
parallelism kinds must compose with the round loop, the flat-ndarray param
codec, aggregation strategies, and client-state plumbing — not just the
standalone Trainer. (The reference federates only dense DP/FSDP/TP models;
these paths are beyond-reference, so the integration anchor is this repo's
own dense federated behavior.)
"""

import numpy as np

from tests.test_federation import make_app, make_cfg


def test_fed_rounds_with_moe_model(tmp_path):
    """Federated rounds over an MoE model: router/expert params ride the
    codec + aggregation like any other leaves; losses stay finite."""
    cfg = make_cfg(tmp_path, n_rounds=2)
    cfg.model.mlp = "moe"
    cfg.model.moe_num_experts = 4
    cfg.model.moe_top_k = 2
    cfg.validate()
    app = make_app(cfg, tmp_path)
    history = app.run()
    assert len(history.series("server/round_time")) == 2
    assert all(np.isfinite(v) for _, v in history.series("server/pseudo_grad_norm"))
    # the aggregated global params still carry the expert leaves
    names = list(app.metadata.names)
    assert any("moe_up" in n for n in names)
    assert any("router" in n for n in names)
    app.driver.shutdown()


def test_fed_rounds_with_pipelined_client(tmp_path):
    """Federated rounds where each client trains through the GPipe pipeline
    (mesh.pipe=2 on the virtual device mesh): same TrainState layout means
    the codec/strategy path is untouched."""
    from photon_tpu.config.schema import MeshConfig

    cfg = make_cfg(tmp_path, n_rounds=2)
    cfg.mesh = MeshConfig(pipe=2)
    cfg.train.device_microbatch_size = 2  # auto is rejected under pipe
    cfg.validate()
    app = make_app(cfg, tmp_path)
    history = app.run()
    assert len(history.series("server/round_time")) == 2
    assert all(np.isfinite(v) for _, v in history.series("server/pseudo_grad_norm"))
    app.driver.shutdown()
