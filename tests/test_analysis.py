"""photon-lint suite (ISSUE 6): the static rule engine + dynamic detectors.

Contract per rule family: (a) a seeded-violation fixture MUST be flagged,
(b) the idiomatic spelling of the same code MUST pass, and (c) the current
photon_tpu tree MUST be clean (zero unsuppressed findings against the
checked-in baseline) — so a rule regression, a new violation, or baseline
rot each fail a different, named test.

The dynamic half: a deliberate lock-order inversion must be caught, a
consistent order must not; a steady-state retrace must be caught, a cache
hit must not; and — telemetry's hook-site discipline — both detectors must
be one ``None`` check when not installed.
"""

import json
import pathlib
import textwrap
import threading

import pytest

import photon_tpu
from photon_tpu.analysis import runtime as rt
from photon_tpu.analysis.cli import DEFAULT_BASELINE, main as lint_main
from photon_tpu.analysis.core import (
    NameRegistry,
    analyze_paths,
    load_baseline,
    write_baseline,
)

pytestmark = pytest.mark.lint

PKG = pathlib.Path(photon_tpu.__file__).resolve().parent


def _lint(tmp_path, src, select=None, name="snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(src))
    return analyze_paths([str(f)], baseline=None, select=select).unsuppressed


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# rule family 1: kpi-registry
# ---------------------------------------------------------------------------


def test_kpi_registry_flags_literals(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import telemetry

        def record_sites(history, tracer):
            history.record(1, {"server/round_time": 1.0})       # stringly
            history.record(1, {"server/definitely_a_typo": 1})  # unknown
            tracer.add_span("client/fit_time", 0.0, 1.0)        # stringly
            telemetry.emit_event(f"chaos/{1}")                  # f-string
        """,
        select=["kpi-registry"],
    )
    assert _rules(found) == {
        "kpi-registry/stringly-name",
        "kpi-registry/unregistered-name",
        "kpi-registry/fstring-name",
    }
    assert len(found) == 4


def test_kpi_registry_constants_pass(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import telemetry
        from photon_tpu.utils.profiling import CHAOS_EVENT_PREFIX, ROUND_TIME

        def record_sites(history, tracer, kind, metrics):
            history.record(1, {ROUND_TIME: 1.0})
            history.record(1, metrics)             # dynamic dict: not static
            tracer.add_span(ROUND_TIME, 0.0, 1.0)
            telemetry.emit_event(CHAOS_EVENT_PREFIX + kind)
        """,
        select=["kpi-registry"],
    )
    assert found == []


def test_registry_parse_matches_runtime_registry():
    """The statically parsed constants agree with the live module — the
    lint and the runtime registry test can never drift apart."""
    from photon_tpu.utils import profiling

    reg = NameRegistry.parse(PKG / "utils" / "profiling.py")
    assert set(profiling.registered_metric_names()) <= set(reg.values)
    assert reg.dynamic_patterns == profiling.DYNAMIC_METRIC_PATTERNS
    assert reg.is_registered("server/round_time")
    assert reg.is_registered("server/anything_norm")  # dynamic family
    assert not reg.is_registered("server/not_a_metric")


# ---------------------------------------------------------------------------
# rule family 1b: metric-discipline (ISSUE 10)
# ---------------------------------------------------------------------------


def test_metric_discipline_flags_literals(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import telemetry

        def sites(hub, health):
            hub.counter("serve/ttft_s")                    # stringly
            hub.histogram("serve/definitely_a_typo")       # unknown
            telemetry.metric_observe(f"serve/{1}_s", 0.1)  # f-string
            health.alert("alert/nonfinite", plane="federation")  # stringly
        """,
        select=["metric-discipline"],
    )
    assert _rules(found) == {
        "metric-discipline/stringly-name",
        "metric-discipline/unregistered-name",
        "metric-discipline/fstring-name",
    }
    assert len(found) == 4


def test_metric_discipline_constants_pass(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import telemetry
        from photon_tpu.utils.profiling import (
            ALERT_NONFINITE, SERVE_TTFT_S, SPANS_DROPPED,
        )

        def sites(hub, health, name):
            hub.counter(SPANS_DROPPED).inc()
            hub.histogram(SERVE_TTFT_S).observe(0.1)
            hub.gauge(name)                      # dynamic name: not static
            telemetry.metric_observe(SERVE_TTFT_S, 0.1)
            telemetry.metric_inc(SPANS_DROPPED)
            health.alert(ALERT_NONFINITE, plane="federation")
        """,
        select=["metric-discipline"],
    )
    assert found == []


# ---------------------------------------------------------------------------
# rule family 2: hook-gating
# ---------------------------------------------------------------------------


def test_hook_gating_flags_unguarded_and_chained(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import chaos, telemetry

        def unguarded():
            tr = telemetry.active()
            tr.drain()

        def chained():
            return chaos.active().tcp_plan()

        def guard_too_late():
            tr2 = telemetry.active()
            tr2.drain()  # crashes when disabled: the guard below can't help
            if tr2 is not None:
                tr2.flush()

        def guard_falls_through():
            tr3 = telemetry.active()
            if tr3 is None:
                print("disabled")  # no return: tr3 is STILL None below
            tr3.drain()

        def or_is_not_a_guard(fallback):
            tr4 = telemetry.active()
            x = tr4 or fallback
            tr4.drain()
        """,
        select=["hook-gating"],
    )
    assert _rules(found) == {"hook-gating/unguarded", "hook-gating/chained-active"}
    assert sum(f.rule == "hook-gating/unguarded" for f in found) == 4


def test_hook_gating_guarded_passes(tmp_path):
    found = _lint(
        tmp_path,
        """
        from photon_tpu import telemetry

        def early_return():
            tr = telemetry.active()
            if tr is None:
                return
            tr.drain()

        def closure_guard():
            tracer = telemetry.active()
            def worker():
                if tracer is not None:
                    tracer.drain()
            return worker

        def truthiness():
            log = telemetry.events_active()
            if log:
                log.drain()

        def compound_or_early_return():
            tr = telemetry.active()
            if tr is None or not tr.piggyback:
                return
            tr.drain()

        def and_shortcircuit():
            tr = telemetry.active()
            return tr and tr.drain()
        """,
        select=["hook-gating"],
    )
    assert found == []


# ---------------------------------------------------------------------------
# rule family 3: retrace-hazard
# ---------------------------------------------------------------------------


def test_retrace_hazard_flags_syncs_branches_mutation(tmp_path):
    found = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def step(x, y):
            if x > 0:                      # traced branch
                y = float(x)               # scalar cast
            z = np.asarray(y)              # numpy materialization
            return z.item()                # host sync

        class Engine:
            @jax.jit
            def step(self, tokens):
                self.cache = tokens        # self mutation under trace
                return tokens
        """,
        select=["retrace-hazard"],
    )
    assert _rules(found) == {
        "retrace-hazard/traced-branch",
        "retrace-hazard/host-sync",
        "retrace-hazard/self-mutation",
    }
    assert sum(f.rule.endswith("host-sync") for f in found) == 3


def test_retrace_hazard_static_and_shape_uses_pass(tmp_path):
    found = _lint(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def bucketed(x, n):
            if n > 8:                       # static arg: fine
                x = x[:n]
            if x.shape[0] > 4:              # shape read: static under trace
                x = x * 2
            if x is None:                   # None check: static
                return jnp.zeros(())
            return int(x.shape[0]) + x.sum()

        def wrapped(state, batch):
            return state + batch.sum()

        step = jax.jit(wrapped, donate_argnums=(0,))
        """,
        select=["retrace-hazard"],
    )
    assert found == []


def test_retrace_hazard_sees_jit_wrapping_call(tmp_path):
    found = _lint(
        tmp_path,
        """
        import jax

        def step_fn(state, tok):
            return state, float(tok)

        _step = jax.jit(step_fn)
        """,
        select=["retrace-hazard"],
    )
    assert _rules(found) == {"retrace-hazard/host-sync"}


# ---------------------------------------------------------------------------
# rule family 4: concurrency
# ---------------------------------------------------------------------------


def test_concurrency_fixture(tmp_path):
    found = _lint(
        tmp_path,
        """
        import os
        import threading

        def bare(lock):
            lock.acquire()
            lock.release()

        def fire_and_forget():
            threading.Thread(target=print).start()

        def swallow():
            try:
                pass
            except:
                pass
            try:
                pass
            except Exception:
                pass
            os._exit(3)
        """,
        select=["concurrency"],
    )
    assert _rules(found) == {
        "concurrency/bare-acquire",
        "concurrency/unnamed-thread",
        "concurrency/unowned-thread",
        "concurrency/swallowed-exception",
        "concurrency/os-exit",
    }


def test_concurrency_idiomatic_passes(tmp_path):
    found = _lint(
        tmp_path,
        """
        import threading

        def scoped(lock):
            with lock:
                pass

        def try_finally(lock):
            lock.acquire(timeout=1)
            try:
                pass
            finally:
                lock.release()

        class Owner:
            def start(self):
                self._thread = threading.Thread(
                    target=print, name="owned", daemon=True
                )
                self._thread.start()

            def close(self):
                self._thread.join(timeout=5)

        def narrow():
            try:
                pass
            except OSError:
                pass  # typed-narrow swallow is allowed
        """,
        select=["concurrency"],
    )
    assert found == []


# ---------------------------------------------------------------------------
# rule family 5: transport-discipline
# ---------------------------------------------------------------------------


def test_transport_discipline_fixture(tmp_path):
    found = _lint(
        tmp_path,
        """
        import pickle

        def raw_wire(sock):
            data = sock.recv(4096)
            return pickle.loads(data)
        """,
        select=["transport-discipline"],
    )
    assert _rules(found) == {
        "transport-discipline/raw-pickle",
        "transport-discipline/raw-socket-read",
    }


def test_transport_discipline_framed_conn_passes(tmp_path):
    found = _lint(
        tmp_path,
        """
        def framed(conn):
            return conn.recv()  # SocketConn/Connection: the framed path
        """,
        select=["transport-discipline"],
    )
    assert found == []


# ---------------------------------------------------------------------------
# suppression + baseline + CLI
# ---------------------------------------------------------------------------


def test_inline_suppression_same_and_next_line(tmp_path):
    found = _lint(
        tmp_path,
        """
        import os

        def a():
            os._exit(1)  # photon-lint: ignore[concurrency/os-exit]

        def b():
            # photon-lint: ignore[concurrency]
            os._exit(2)

        def c():
            os._exit(3)  # no suppression: still flagged
        """,
        select=["concurrency"],
    )
    assert len(found) == 1 and found[0].rule == "concurrency/os-exit"
    assert found[0].snippet.startswith("os._exit(3)")


def test_baseline_roundtrip_and_staleness(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text("import os\nos._exit(1)\n")
    base = tmp_path / "baseline.json"
    rep = analyze_paths([str(bad)], baseline=None)
    assert len(rep.unsuppressed) == 1
    write_baseline(base, rep.unsuppressed)
    entries = load_baseline(base)
    assert len(entries) == 1 and entries[0].rule == "concurrency/os-exit"

    rep2 = analyze_paths([str(bad)], baseline=base)
    assert rep2.ok and not rep2.stale_baseline
    assert sum(1 for f in rep2.findings if f.baselined) == 1

    # the offending line changes -> the entry is STALE and the (new)
    # finding is unsuppressed again: baselines can't mask fresh violations
    bad.write_text("import os\nos._exit(2)\n")
    rep3 = analyze_paths([str(bad)], baseline=base)
    assert not rep3.ok
    assert [e.fingerprint for e in rep3.stale_baseline] == [entries[0].fingerprint]


def test_partial_scan_keeps_unscanned_baseline_entries(tmp_path):
    """Scanning a subset of the tree must neither report unscanned files'
    baseline entries as stale nor delete them on --write-baseline."""
    a = tmp_path / "a.py"
    a.write_text("import os\nos._exit(1)\n")
    b = tmp_path / "b.py"
    b.write_text("import os\nos._exit(2)\n")
    base = tmp_path / "baseline.json"
    rep_all = analyze_paths([str(a), str(b)], baseline=None)
    write_baseline(base, rep_all.unsuppressed, scanned_paths=rep_all.scanned_paths)
    n_all = len(load_baseline(base))
    assert n_all == 2

    # partial scan: b.py's entry is invisible, NOT stale
    rep_a = analyze_paths([str(a)], baseline=base)
    assert rep_a.ok and not rep_a.stale_baseline

    # partial --write-baseline path: b.py's entry survives the rewrite
    write_baseline(
        base,
        [f for f in rep_a.findings if not f.suppressed],
        scanned_paths=rep_a.scanned_paths,
    )
    assert len(load_baseline(base)) == n_all

    # a genuinely stale entry in a SCANNED file still fails
    a.write_text("x = 1\n")
    rep_fixed = analyze_paths([str(a)], baseline=base)
    assert not rep_fixed.ok and len(rep_fixed.stale_baseline) == 1


def test_string_join_is_not_thread_ownership(tmp_path):
    """A `", ".join(parts)` must not satisfy the unowned-thread rule; a
    join on a Thread-assigned name or *thread*-named attribute must."""
    found = _lint(
        tmp_path,
        """
        import threading

        def fire_and_forget(parts):
            threading.Thread(target=print, name="t").start()
            return ", ".join(parts)
        """,
        select=["concurrency"],
    )
    assert _rules(found) == {"concurrency/unowned-thread"}

    found = _lint(
        tmp_path,
        """
        import threading

        def owned():
            t = threading.Thread(target=print, name="t")
            t.start()
            t.join()
        """,
        select=["concurrency"],
    )
    assert found == []


def test_partially_fixed_count_entry_goes_stale(tmp_path):
    """Fixing ONE of two identical baselined lines must surface the entry
    as stale — leftover count budget would otherwise silently baseline the
    NEXT identical violation with no human re-justifying it."""
    mod = tmp_path / "mod.py"
    mod.write_text("import os\nos._exit(1)\nos._exit(1)\n")
    base = tmp_path / "baseline.json"
    rep = analyze_paths([str(mod)], baseline=None)
    write_baseline(base, rep.unsuppressed)
    assert load_baseline(base)[0].count == 2
    assert analyze_paths([str(mod)], baseline=base).ok

    mod.write_text("import os\nos._exit(1)\n")  # one of the two fixed
    rep2 = analyze_paths([str(mod)], baseline=base)
    assert not rep2.ok and len(rep2.stale_baseline) == 1


def test_suppression_syntax_in_string_is_inert(tmp_path):
    """Docs QUOTING the ignore syntax inside a string literal must not
    suppress anything — only real comment tokens register suppressions.
    Both stringly spellings that fooled the line-regex scanner: a string
    ending on the comment-shaped line (next-line form) and a string on the
    violating line itself (same-line form)."""
    found = _lint(
        tmp_path,
        '''
        import os

        DOC = """
        # photon-lint: ignore[concurrency]"""
        os._exit(1)

        s = "# photon-lint: ignore[concurrency]"; os._exit(2)
        ''',
        select=["concurrency"],
    )
    assert _rules(found) == {"concurrency/os-exit"} and len(found) == 2


def test_select_scan_keeps_unselected_baseline_entries(tmp_path):
    """A --select run can only judge entries of the selected families: it
    must neither report other families' entries as stale nor delete them
    on --write-baseline."""
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import os, pickle\n"
        "def f(data):\n"
        "    os._exit(1)\n"
        "    return pickle.loads(data)\n"
    )
    base = tmp_path / "baseline.json"
    rep = analyze_paths([str(mod)], baseline=None)
    write_baseline(base, rep.unsuppressed)
    assert {e.rule.split("/", 1)[0] for e in load_baseline(base)} == {
        "concurrency", "transport-discipline",
    }

    rep_sel = analyze_paths([str(mod)], baseline=base, select=["concurrency"])
    assert rep_sel.ok and not rep_sel.stale_baseline

    write_baseline(
        base,
        [f for f in rep_sel.findings if not f.suppressed],
        scanned_paths=rep_sel.scanned_paths,
        selected_families=frozenset(["concurrency"]),
    )
    assert {e.rule.split("/", 1)[0] for e in load_baseline(base)} == {
        "concurrency", "transport-discipline",
    }


def test_overlapping_paths_scan_each_file_once(tmp_path):
    """dir + file-inside-dir must not double-scan: duplicate findings blow
    the baseline's per-fingerprint count budget (FAIL on a clean tree) and
    inflate counts on --write-baseline."""
    mod = tmp_path / "mod.py"
    mod.write_text("import os\nos._exit(1)\n")
    base = tmp_path / "baseline.json"
    rep = analyze_paths([str(mod)], baseline=None)
    write_baseline(base, rep.unsuppressed)

    rep2 = analyze_paths([str(tmp_path), str(mod)], baseline=base)
    assert rep2.n_files == 1
    assert rep2.ok, [f.format() for f in rep2.unsuppressed]
    assert load_baseline(base)[0].count == 1


def test_cli_missing_or_empty_paths_are_usage_errors(tmp_path):
    assert lint_main([str(tmp_path / "no_such_dir")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert lint_main([str(empty), "--no-baseline"]) == 2


def test_checked_in_baseline_is_justified():
    entries = load_baseline(DEFAULT_BASELINE)
    assert entries, "baseline file missing"
    for e in entries:
        assert e.justification and "TODO" not in e.justification, e.path


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nos._exit(1)\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint_main([str(bad), "--no-baseline"]) == 1
    assert lint_main([str(good), "--no-baseline"]) == 0
    assert lint_main(["--list-rules"]) == 0
    assert lint_main([str(bad), "--no-baseline", "--json"]) == 1
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["findings"][0]["rule"] == "concurrency/os-exit"


def test_current_tree_is_clean():
    """THE acceptance gate: zero unsuppressed findings on photon_tpu/
    against the checked-in baseline, and no stale baseline entries."""
    rep = analyze_paths([str(PKG)], baseline=DEFAULT_BASELINE)
    assert rep.unsuppressed == [], "\n".join(f.format() for f in rep.unsuppressed)
    assert rep.stale_baseline == [], [e.path for e in rep.stale_baseline]
    assert rep.n_files > 100  # the walk actually covered the tree


# ---------------------------------------------------------------------------
# dynamic: lock-order recorder
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_detectors():
    yield
    rt.uninstall_lock_order()
    rt.uninstall_retrace_sentinel()


def test_lock_order_inversion_detected():
    rec = rt.install_lock_order()
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    def ba():
        with lock_b:
            with lock_a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn, name="inv", daemon=True)
        t.start()
        t.join()
    with pytest.raises(rt.LockOrderViolation, match="inversion"):
        rec.check()


def test_lock_order_consistent_is_green():
    with rt.lock_order_guard() as rec:
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        for _ in range(3):
            with lock_a:
                with lock_b:
                    pass
    assert rec.n_locks >= 2
    assert rt.lock_order_active() is None  # guard uninstalled


def test_surviving_wrappers_go_quiet_after_uninstall():
    """Locks created while installed outlive the recorder (their owners
    keep holding them) — after uninstall they must degrade to a None check,
    not keep feeding the dead recorder's graph on every acquire."""
    rec = rt.install_lock_order()
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    with lock_a:
        pass
    n_before = rec.n_acquires
    assert n_before >= 1
    rt.uninstall_lock_order()
    with lock_a:  # wrappers still work, recording is off
        with lock_b:
            pass
    assert rec.n_acquires == n_before
    assert not rec.edges()


def test_lock_order_tracks_condition_protocol():
    """Condition on tracked Lock AND tracked RLock (the ContinuousBatcher
    shape): wait/notify must round-trip through the wrappers."""
    rec = rt.install_lock_order()
    cond_default = threading.Condition()  # internally RLock()
    with cond_default:
        cond_default.notify_all()
    cond_lock = threading.Condition(threading.Lock())
    with cond_lock:
        cond_lock.wait(timeout=0.01)
    ev = threading.Event()
    ev.set()
    assert rec.n_locks >= 2
    rec.check()  # no inversion


# ---------------------------------------------------------------------------
# dynamic: retrace sentinel
# ---------------------------------------------------------------------------


def test_retrace_sentinel_catches_steady_state_compile():
    import jax
    import jax.numpy as jnp

    s = rt.install_retrace_sentinel()
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones((4,)))
    assert s.compiles >= 1  # warmup observed
    s.mark_steady()
    f(jnp.ones((4,)))  # cache hit
    rt.steady_point("tick")
    assert s.violations == []
    f(jnp.ones((5,)))  # new shape: retrace (the ones() itself compiles too)
    rt.steady_point("tick")
    with pytest.raises(rt.RetraceViolation, match="tick: "):
        s.check()


def test_retrace_sentinel_steady_after_points():
    import jax
    import jax.numpy as jnp

    s = rt.install_retrace_sentinel()
    s.mark_steady_after(2)
    g = jax.jit(lambda x: x + 1)
    for i in (3, 4):  # two warmup iterations, each compiles
        g(jnp.ones((i,)))
        rt.steady_point("warm")
    assert s.steady
    g(jnp.ones((3,)))  # steady cache hit
    rt.steady_point("steady")
    s.check()  # green


def test_retrace_sentinel_warmup_check_does_not_consume_point_budget():
    """check() during warmup must be inert: only real steady_point hook
    sites advance mark_steady_after's budget, so a per-round assertion
    can't flip steady early and bill legitimate warmup compiles."""
    import jax
    import jax.numpy as jnp

    s = rt.install_retrace_sentinel()
    s.mark_steady_after(2)
    g = jax.jit(lambda x: x - 1)
    g(jnp.ones((3,)))
    rt.steady_point("warm")
    s.check()  # mid-warmup assertion: must not count as the 2nd point
    assert not s.steady
    g(jnp.ones((4,)))  # second warmup compile, still legitimate
    rt.steady_point("warm")
    assert s.steady
    g(jnp.ones((3,)))  # cache hit
    rt.steady_point("steady")
    s.check()  # green


def test_disabled_detectors_are_none_checks():
    """Telemetry's hook-site discipline, asserted the same way: with
    nothing installed the hooks are a single None check and the threading
    factories are the real C ones."""
    assert rt.lock_order_active() is None
    assert rt.retrace_active() is None
    rt.steady_point("anything")  # must not raise, must not allocate state
    assert threading.Lock.__module__ == "_thread"
    rec = rt.install_lock_order()
    assert threading.Lock == rec._make_lock  # == : bound methods compare by (self, func)
    rt.uninstall_lock_order()
    assert threading.Lock.__module__ == "_thread"
