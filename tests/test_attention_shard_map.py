"""Pallas flash attention on a multi-device mesh (round-5 fix).

Mosaic kernels cannot be auto-partitioned by GSPMD — `multihead_attention`
must wrap the pallas call in `shard_map` on a sharded mesh (exact: the
kernel is independent per batch row and per head). Discovered by the
offline sharded AOT compile (`scripts/aot_compile_check.py --mesh fsdp=4`),
which raised `NotImplementedError: Mosaic kernels cannot be automatically
partitioned` on the pre-fix dispatch; never caught before because off-TPU
the dispatch silently falls back to XLA attention.

Runs the kernel in interpret mode on the conftest's 8 virtual CPU devices;
the reference is the plain XLA attention on the same global inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import MeshConfig
from photon_tpu.ops import attention as attn_mod
from photon_tpu.ops.attention import multihead_attention, xla_attention
from photon_tpu.parallel.context import use_mesh
from photon_tpu.parallel.mesh import make_mesh

B, S, H, D = 4, 256, 4, 64


@pytest.fixture()
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _mesh(**axes):
    return make_mesh(MeshConfig(**axes))


@pytest.mark.parametrize("axes", [
    {"data": 2, "fsdp": 2},              # batch sharded two ways
    {"data": 2, "fsdp": 2, "tensor": 2},  # batch + head sharded
])
def test_sharded_flash_matches_xla(qkv, axes, monkeypatch):
    q, k, v = qkv
    ref = xla_attention(q, k, v, causal=True, alibi=False)
    monkeypatch.setattr(attn_mod, "xla_attention", None)  # must not be used
    with use_mesh(_mesh(**axes)):
        out = multihead_attention(q, k, v, impl="pallas", causal=True,
                                  alibi=False, block_q=128, block_k=128,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_sharded_flash_alibi_batch_axes_only(qkv, monkeypatch):
    # ALiBi is safe under batch sharding (head dim unsharded)
    q, k, v = qkv
    ref = xla_attention(q, k, v, causal=True, alibi=True)
    with use_mesh(_mesh(data=2, fsdp=2)):
        out = multihead_attention(q, k, v, impl="pallas", causal=True,
                                  alibi=True, block_q=128, block_k=128,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_alibi_with_tensor_sharding_uses_global_slopes(qkv):
    # in-kernel ALiBi derives slopes from the head index; under a
    # head-sharded mesh each shard must slice ITS rows of the global slope
    # table (a per-shard restart of the slope sequence would silently bias
    # heads wrong — only a global-reference comparison catches it)
    q, k, v = qkv
    ref = xla_attention(q, k, v, causal=True, alibi=True)
    with use_mesh(_mesh(data=2, tensor=2)):
        out = multihead_attention(q, k, v, impl="pallas", causal=True,
                                  alibi=True, block_q=128, block_k=128,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_sharded_gqa_flash_matches_xla(qkv):
    # GQA k/v at native width through the shard_map path: kv heads split
    # over the tensor axis (h_kv=2, tensor=2 → one kv head per shard, its
    # q group alongside via the same head-major order)
    q, _, _ = qkv
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // 2, axis=2)
    ref = xla_attention(q, rep(k), rep(v), causal=True, alibi=False)
    with use_mesh(_mesh(data=2, tensor=2)):
        out = multihead_attention(q, k, v, impl="pallas", causal=True,
                                  alibi=False, block_q=128, block_k=128,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_sharded_gqa_alibi_gradients_match_xla(qkv):
    # the riskiest composition: GQA dkv backward (kv-row-major qrow
    # indexing) + ALiBi global-slope slicing, under a tensor-sharded mesh
    q, _, _ = qkv
    rng = np.random.default_rng(5)
    k = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // 2, axis=2)

    def loss_flash(q, k, v):
        with use_mesh(_mesh(data=2, tensor=2)):
            o = multihead_attention(q, k, v, impl="pallas", causal=True,
                                    alibi=True, block_q=128, block_k=128,
                                    interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = xla_attention(q, rep(k), rep(v), causal=True, alibi=True)
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2)


def test_sharded_flash_gradients_match_xla(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        with use_mesh(_mesh(data=2, fsdp=2)):
            o = multihead_attention(q, k, v, impl="pallas", causal=True,
                                    alibi=False, block_q=128, block_k=128,
                                    interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v, causal=True, alibi=False)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2)


def test_ring_dispatch_mqa_kv_indivisible_by_tensor(qkv):
    # MQA (1 kv head) with tensor=2 and a sequence axis: kv heads can't
    # split over tensor, so the dispatch must replicate kv BEFORE ring
    # attention (ring's own spec would otherwise silently drop head
    # sharding for q too)
    q, _, _ = qkv
    rng = np.random.default_rng(11)
    k = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H, axis=2)
    ref = xla_attention(q, rep(k), rep(v), causal=True, alibi=False)
    with use_mesh(_mesh(tensor=2, sequence=2)):
        out = multihead_attention(q, k, v, impl="ring", causal=True,
                                  alibi=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
