"""Config presets: all recipes validate; shapes track the reference YAMLs."""

import pytest

from photon_tpu.config import list_presets, load_preset


def test_all_presets_validate():
    names = list_presets()
    assert {"mpt-125m", "mpt-350m", "mpt-760m", "mpt-1b", "mpt-3b", "mpt-7b"} <= set(names)
    for name in names:
        cfg = load_preset(name)
        assert cfg.model.d_model % cfg.model.n_heads == 0, name
        assert cfg.scheduler.t_max > 100


def test_125m_matches_reference_recipe():
    cfg = load_preset("mpt-125m")
    m = cfg.model
    assert (m.d_model, m.n_layers, m.n_heads, m.max_seq_len, m.vocab_size) == (768, 12, 12, 2048, 50368)
    assert cfg.optimizer.name == "adopt" and cfg.optimizer.lr == 6.0e-4
    assert cfg.train.global_batch_size == 256 and cfg.scheduler.t_max == 4800


def test_1b_matches_reference_recipe():
    cfg = load_preset("mpt-1b")
    m = cfg.model
    assert (m.d_model, m.n_layers, m.n_heads) == (2048, 24, 16)
    assert m.d_head == 128  # flash-attn-friendly head dim (reference note)
    assert m.remat  # activation checkpointing on at 1B
    assert cfg.optimizer.name == "adamw"


def test_preset_overrides_merge():
    cfg = load_preset("mpt-125m", fl={"n_rounds": 10}, seed=3)
    assert cfg.fl.n_rounds == 10 and cfg.seed == 3
    assert cfg.model.d_model == 768


def test_unknown_preset_raises():
    with pytest.raises(ValueError):
        load_preset("mpt-999t")
