"""Ring attention parity: shard_map ring over the sequence axis must equal
full attention exactly (same math, online-softmax merge), forward and grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.config.schema import MeshConfig
from photon_tpu.ops.attention import xla_attention
from photon_tpu.ops.ring_attention import (
    _merge_partials,
    ring_attention,
    xla_chunk_attention,
)
from photon_tpu.parallel.mesh import make_mesh

B, S, H, D = 2, 64, 2, 16


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, S, H, D)), dtype)  # noqa: E731
    return mk(), mk(), mk()


def test_chunk_attention_matches_full():
    q, k, v = _qkv()
    o_full = xla_attention(q, k, v, causal=True)
    o_chunk, lse = xla_chunk_attention(q, k, v, q_start=0, k_start=0, causal=True)
    np.testing.assert_allclose(np.asarray(o_chunk), np.asarray(o_full), rtol=1e-5, atol=1e-5)
    assert lse.shape == (B, S, H)


def test_merge_partials_reconstructs_softmax():
    """Splitting k into two chunks and merging must equal one-shot attention."""
    q, k, v = _qkv(1)
    half = S // 2
    o1, l1 = xla_chunk_attention(q, k[:, :half], v[:, :half], q_start=0, k_start=0, causal=True)
    o2, l2 = xla_chunk_attention(q, k[:, half:], v[:, half:], q_start=0, k_start=half, causal=True)
    o, _ = _merge_partials(o1, l1, o2, l2)
    o_full = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_full), rtol=1e-5, atol=1e-5)


def test_fully_masked_chunk_is_neutral():
    """A future chunk (all masked) must not perturb the merge."""
    q, k, v = _qkv(2)
    o1, l1 = xla_chunk_attention(q, k, v, q_start=0, k_start=0, causal=True)
    # chunk entirely in the future relative to every query
    o2, l2 = xla_chunk_attention(q, k, v, q_start=0, k_start=S + 100, causal=True)
    assert np.all(np.asarray(o2) == 0)
    assert np.all(np.asarray(l2) < -1e29)
    o, _ = _merge_partials(o1, l1, o2, l2)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o1), rtol=1e-6)


@pytest.mark.parametrize("ring", [2, 4])
def test_ring_attention_matches_full(ring):
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, tensor=1, sequence=ring))
    q, k, v = _qkv(3)
    spec = P(("data", "fsdp"), "sequence", None, None)
    sh = NamedSharding(mesh, spec)
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    o_ring = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, impl="xla")
    )(qs, ks, vs)
    o_full = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o_ring), np.asarray(o_full), rtol=1e-5, atol=1e-5)


def test_ring_attention_grads_match_full():
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, sequence=4))
    q, k, v = _qkv(4)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, impl="xla")
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    def loss_full(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v, causal=True).astype(jnp.float32)))

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), rtol=1e-4, atol=1e-4)


def test_ring_size_one_is_plain_attention():
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, sequence=1))
    q, k, v = _qkv(5)
    o = ring_attention(q, k, v, mesh, causal=True, impl="xla")
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(xla_attention(q, k, v, causal=True)), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("alibi", [False, True])
def test_ring_gqa_matches_full(alibi):
    """GQA kv rotates the ring at native (grouped) width — the merged result
    must equal full attention on replicated kv, incl. global-position ALiBi."""
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, sequence=4))
    q, _, _ = _qkv(6)
    rng = np.random.default_rng(7)
    h_kv = 1
    k = jnp.asarray(rng.normal(size=(B, S, h_kv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, h_kv, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // h_kv, axis=2)  # noqa: E731

    o_ring = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, impl="xla",
                                       alibi=alibi)
    )(q, k, v)
    o_full = xla_attention(q, rep(k), rep(v), causal=True, alibi=alibi)
    np.testing.assert_allclose(np.asarray(o_ring), np.asarray(o_full),
                               rtol=1e-5, atol=1e-5)


def test_ring_gqa_grads_match_full():
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, sequence=4))
    q, _, _ = _qkv(8)
    rng = np.random.default_rng(9)
    k = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H, axis=2)  # noqa: E731

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, impl="xla")
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    def loss_full(q, k, v):
        o = xla_attention(q, rep(k), rep(v), causal=True)
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        assert gr.shape == gf.shape
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)


def test_ring_gqa_with_tensor_sharded_heads():
    """GQA widths through ring's head-sharding path: the tensor axis size
    divides both h and h_kv, so the spec keeps heads sharded AND kv rides
    the ring at grouped width per shard."""
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=2, sequence=2))
    q, _, _ = _qkv(10)  # [B, S, H=2, D]
    rng = np.random.default_rng(11)
    k = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)  # h_kv == H
    # make it GQA by DOUBLING q heads: H_q=4, h_kv=2, group 2 — both divide
    # tensor=2
    q4 = jnp.concatenate([q, q * 0.5], axis=2)
    v = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, 2, axis=2)  # noqa: E731

    o_ring = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, impl="xla")
    )(q4, k, v)
    o_full = xla_attention(q4, rep(k), rep(v), causal=True)
    np.testing.assert_allclose(np.asarray(o_ring), np.asarray(o_full),
                               rtol=1e-5, atol=1e-5)
