"""Checkpoint subsystem tests: store atomicity/listing, server round
save/resume (negative indexing, validity, GC, cross-run import), client
skip-if-done semantics."""

import numpy as np
import pytest

from photon_tpu.checkpoint import (
    ClientCheckpointManager,
    FileStore,
    ServerCheckpointManager,
    arrays_to_npz,
    npz_to_arrays,
)
from photon_tpu.codec import ParamsMetadata


def _params(seed=0, n=3):
    rng = np.random.default_rng(seed)
    arrays = [rng.normal(size=(4, 2)).astype(np.float32) for _ in range(n)]
    names = [f"layer_{i}/w" for i in range(n)]
    return ParamsMetadata.from_ndarrays(names, arrays), arrays


def test_filestore_roundtrip(tmp_path):
    s = FileStore(tmp_path / "store")
    s.put("a/b/c.bin", b"hello")
    assert s.exists("a/b/c.bin")
    assert s.get("a/b/c.bin") == b"hello"
    s.put("a/b/d.bin", b"x")
    assert s.list("a") == ["a/b/c.bin", "a/b/d.bin"]
    s.delete("a/b/c.bin")
    assert not s.exists("a/b/c.bin")
    with pytest.raises(ValueError):
        s.put("../escape", b"no")


def test_npz_roundtrip_preserves_order_and_dtypes():
    meta, arrays = _params()
    arrays[1] = arrays[1].astype(np.float64)
    meta = ParamsMetadata.from_ndarrays(meta.names, arrays)
    m2, a2 = npz_to_arrays(arrays_to_npz(meta, arrays))
    assert m2.names == meta.names
    for x, y in zip(arrays, a2):
        np.testing.assert_array_equal(x, y)
        assert x.dtype == y.dtype


def test_server_checkpoint_save_load_resume(tmp_path):
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    keys = ("momentum",)
    for r in [0, 1, 2]:
        momenta = [np.full_like(a, r) for a in params]
        mgr.save_round(r, meta, params, {"momentum": momenta}, {"round": r, "steps": r * 128})
    assert mgr.list_rounds() == [0, 1, 2]
    assert mgr.valid_rounds(keys) == [0, 1, 2]

    # negative resume indexing
    assert mgr.resolve_resume_round(-1, keys) == 2
    assert mgr.resolve_resume_round(-2, keys) == 1
    assert mgr.resolve_resume_round(1, keys) == 1
    with pytest.raises(FileNotFoundError):
        mgr.resolve_resume_round(7, keys)
    with pytest.raises(FileNotFoundError):
        mgr.resolve_resume_round(-5, keys)

    m, p, st, server_state = mgr.load_round(2, keys)
    assert m.names == meta.names
    np.testing.assert_array_equal(st["momentum"][0], np.full_like(params[0], 2))
    assert server_state == {"round": 2, "steps": 256}


def test_server_checkpoint_validity_and_gc(tmp_path):
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    keys = ("momentum",)
    for r in range(5):
        mgr.save_round(r, meta, params, {"momentum": params}, {})
    # corrupt round 3: missing momentum -> invalid
    store.delete("run1/server/3/momentum.npz")
    assert mgr.valid_rounds(keys) == [0, 1, 2, 4]
    assert mgr.resolve_resume_round(-2, keys) == 2

    deleted = mgr.cleanup(keep=2, state_keys=keys)
    assert 3 in deleted  # partial round removed too
    assert mgr.valid_rounds(keys) == [2, 4]


@pytest.mark.chaos
def test_round_manifest_checksums(tmp_path):
    """Every object a round writes is CRC'd in manifest.json (written last);
    a bit-flipped object fails verify_round but not the cheap presence
    check (GC must stay cheap, resume must stay safe)."""
    import json

    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    keys = ("momentum",)
    mgr.save_round(1, meta, params, {"momentum": params}, {"round": 1})
    manifest = json.loads(store.get("run1/server/1/manifest.json").decode())
    assert set(manifest["crc32"]) == {
        "current_server_parameters.npz", "momentum.npz", "state.bin",
    }
    assert mgr.is_valid_round(1, keys)
    assert mgr.is_valid_round(1, keys, verify_checksums=True)
    # flip one byte in the params object, bypassing the store API (the
    # bit-rot / torn-write shape chaos.store_bitflip_p injects)
    p = tmp_path / "run1" / "server" / "1" / "current_server_parameters.npz"
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0x40
    p.write_bytes(bytes(raw))
    # verification memoizes per manager (completed rounds are immutable to
    # their writer); at-rest rot like this tamper is caught by the FRESH
    # manager a resume constructs
    fresh = ServerCheckpointManager(store, "run1")
    assert fresh.is_valid_round(1, keys)  # presence-only still true
    assert not fresh.verify_round(1, keys)
    assert not fresh.is_valid_round(1, keys, verify_checksums=True)


@pytest.mark.chaos
def test_resume_skips_corrupt_round(tmp_path):
    """resolve_resume_round(-1) must fall back to the newest checksum-valid
    round instead of resuming garbage; an explicitly requested corrupt
    round raises."""
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    for r in [1, 2, 3]:
        mgr.save_round(r, meta, params, {}, {"round": r})
    p = tmp_path / "run1" / "server" / "3" / "current_server_parameters.npz"
    raw = bytearray(p.read_bytes())
    raw[-1] ^= 0xFF
    p.write_bytes(bytes(raw))
    with pytest.warns(UserWarning, match="checksum"):
        assert mgr.resolve_resume_round(-1) == 2
    with pytest.warns(UserWarning, match="checksum"):
        assert mgr.resolve_resume_round(-2) == 1
    with pytest.raises(FileNotFoundError, match="checksum"):
        mgr.resolve_resume_round(3)
    with pytest.raises(FileNotFoundError):
        with pytest.warns(UserWarning, match="checksum"):
            mgr.resolve_resume_round(-3)


@pytest.mark.chaos
def test_gc_does_not_count_corrupt_rounds_toward_keep(tmp_path):
    """A bit-flipped newest round must not push the checksum-valid rounds
    (that resume's corruption fallback needs) out of the GC window — and
    the corrupt round itself is kept as forensics, not resumed."""
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    for r in [1, 2, 3]:
        mgr.save_round(r, meta, params, {}, {"round": r})
    p = tmp_path / "run1" / "server" / "3" / "current_server_parameters.npz"
    raw = bytearray(p.read_bytes())
    raw[-1] ^= 0xFF
    p.write_bytes(bytes(raw))
    deleted = mgr.cleanup(keep=1)
    # keep=1 keeps checksum-valid round 2; round 1 is GC'd; corrupt round 3
    # (newer than the newest good round) survives as forensics
    assert deleted == [1]
    assert mgr.list_rounds() == [2, 3]
    with pytest.warns(UserWarning, match="checksum"):
        assert mgr.resolve_resume_round(-1) == 2


@pytest.mark.chaos
def test_verify_cache_invalidated_on_rewrite(tmp_path):
    """A resumed run rewrites rounds above the resume point: the memoized
    verdict for the old (corrupt) bytes must not stick to the fresh write."""
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    mgr.save_round(1, meta, params, {}, {"round": 1})
    p = tmp_path / "run1" / "server" / "1" / "current_server_parameters.npz"
    raw = bytearray(p.read_bytes())
    raw[0] ^= 0x01
    p.write_bytes(bytes(raw))
    assert not mgr.verify_round(1)  # memoized False
    mgr.save_round(1, meta, params, {}, {"round": 1})  # rewrite (resume path)
    assert mgr.verify_round(1)


@pytest.mark.chaos
def test_pre_manifest_rounds_still_resume(tmp_path):
    """Back-compat: rounds written before the manifest existed (cross-run
    imports of old checkpoints) verify vacuously."""
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    mgr.save_round(1, meta, params, {}, {"round": 1})
    store.delete("run1/server/1/manifest.json")
    assert mgr.verify_round(1)
    assert mgr.resolve_resume_round(-1) == 1


@pytest.mark.chaos
def test_filestore_put_leaves_no_tmp(tmp_path):
    """The fsync'd atomic write still cleans up its temp file."""
    s = FileStore(tmp_path / "store")
    s.put("x/y.bin", b"payload")
    assert s.get("x/y.bin") == b"payload"
    leftovers = [p for p in (tmp_path / "store").rglob("*") if ".tmp-" in p.name]
    assert leftovers == []


def test_cross_run_import(tmp_path):
    store = FileStore(tmp_path)
    old = ServerCheckpointManager(store, "old_run")
    meta, params = _params()
    old.save_round(4, meta, params, {}, {"round": 4})
    new = ServerCheckpointManager(store, "new_run")
    assert new.import_run("old_run") == [4]
    _, p, _, st = new.load_round(4)
    np.testing.assert_array_equal(p[0], params[0])
    assert st["round"] == 4


def test_client_checkpoint_skip_if_done(tmp_path):
    store = FileStore(tmp_path)
    mgr = ClientCheckpointManager(store, "run1")
    meta, params = _params()
    for step in [128, 256, 384]:
        mgr.save(cid=3, step=step, params_meta=meta, params=params,
                 extra_state={"loader": {"epoch": 0, "sample_in_epoch": step}})
    assert mgr.steps(3) == [128, 256, 384]
    assert mgr.latest_at_most(3, 300) == 256
    assert mgr.latest_at_most(3, 100) is None
    assert mgr.should_skip_round(3, 384)
    assert not mgr.should_skip_round(3, 512)

    _, p, opt, state = mgr.load(3, 256)
    assert opt is None
    assert state["loader"]["sample_in_epoch"] == 256

    assert mgr.cleanup(3, keep=1) == [128, 256]
    assert mgr.steps(3) == [384]


def test_trainer_opt_state_roundtrip(tiny_trainer):
    """Full TrainState round-trip through the checkpoint arrays path."""
    trainer, batch = tiny_trainer
    trainer.fit([batch, batch], duration_steps=2)
    om, oa = trainer.get_opt_state_arrays()
    pm, pa = trainer.get_parameters()
    step = trainer.step

    trainer2_m, trainer2_a = trainer.get_opt_state_arrays()
    trainer.reset_optimizer()
    changed = any(
        not np.array_equal(x, y)
        for x, y in zip(oa, trainer.get_opt_state_arrays()[1])
    )
    assert changed  # moments were non-zero after 2 steps

    trainer.set_opt_state_arrays(om, oa)
    trainer.set_parameters(pm, pa)
    trainer.set_step(step)
    for x, y in zip(oa, trainer.get_opt_state_arrays()[1]):
        np.testing.assert_allclose(x, y, rtol=1e-6)
    del trainer2_m, trainer2_a
