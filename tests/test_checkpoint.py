"""Checkpoint subsystem tests: store atomicity/listing, server round
save/resume (negative indexing, validity, GC, cross-run import), client
skip-if-done semantics."""

import numpy as np
import pytest

from photon_tpu.checkpoint import (
    ClientCheckpointManager,
    FileStore,
    ServerCheckpointManager,
    arrays_to_npz,
    npz_to_arrays,
)
from photon_tpu.codec import ParamsMetadata


def _params(seed=0, n=3):
    rng = np.random.default_rng(seed)
    arrays = [rng.normal(size=(4, 2)).astype(np.float32) for _ in range(n)]
    names = [f"layer_{i}/w" for i in range(n)]
    return ParamsMetadata.from_ndarrays(names, arrays), arrays


def test_filestore_roundtrip(tmp_path):
    s = FileStore(tmp_path / "store")
    s.put("a/b/c.bin", b"hello")
    assert s.exists("a/b/c.bin")
    assert s.get("a/b/c.bin") == b"hello"
    s.put("a/b/d.bin", b"x")
    assert s.list("a") == ["a/b/c.bin", "a/b/d.bin"]
    s.delete("a/b/c.bin")
    assert not s.exists("a/b/c.bin")
    with pytest.raises(ValueError):
        s.put("../escape", b"no")


def test_npz_roundtrip_preserves_order_and_dtypes():
    meta, arrays = _params()
    arrays[1] = arrays[1].astype(np.float64)
    meta = ParamsMetadata.from_ndarrays(meta.names, arrays)
    m2, a2 = npz_to_arrays(arrays_to_npz(meta, arrays))
    assert m2.names == meta.names
    for x, y in zip(arrays, a2):
        np.testing.assert_array_equal(x, y)
        assert x.dtype == y.dtype


def test_server_checkpoint_save_load_resume(tmp_path):
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    keys = ("momentum",)
    for r in [0, 1, 2]:
        momenta = [np.full_like(a, r) for a in params]
        mgr.save_round(r, meta, params, {"momentum": momenta}, {"round": r, "steps": r * 128})
    assert mgr.list_rounds() == [0, 1, 2]
    assert mgr.valid_rounds(keys) == [0, 1, 2]

    # negative resume indexing
    assert mgr.resolve_resume_round(-1, keys) == 2
    assert mgr.resolve_resume_round(-2, keys) == 1
    assert mgr.resolve_resume_round(1, keys) == 1
    with pytest.raises(FileNotFoundError):
        mgr.resolve_resume_round(7, keys)
    with pytest.raises(FileNotFoundError):
        mgr.resolve_resume_round(-5, keys)

    m, p, st, server_state = mgr.load_round(2, keys)
    assert m.names == meta.names
    np.testing.assert_array_equal(st["momentum"][0], np.full_like(params[0], 2))
    assert server_state == {"round": 2, "steps": 256}


def test_server_checkpoint_validity_and_gc(tmp_path):
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _params()
    keys = ("momentum",)
    for r in range(5):
        mgr.save_round(r, meta, params, {"momentum": params}, {})
    # corrupt round 3: missing momentum -> invalid
    store.delete("run1/server/3/momentum.npz")
    assert mgr.valid_rounds(keys) == [0, 1, 2, 4]
    assert mgr.resolve_resume_round(-2, keys) == 2

    deleted = mgr.cleanup(keep=2, state_keys=keys)
    assert 3 in deleted  # partial round removed too
    assert mgr.valid_rounds(keys) == [2, 4]


def test_cross_run_import(tmp_path):
    store = FileStore(tmp_path)
    old = ServerCheckpointManager(store, "old_run")
    meta, params = _params()
    old.save_round(4, meta, params, {}, {"round": 4})
    new = ServerCheckpointManager(store, "new_run")
    assert new.import_run("old_run") == [4]
    _, p, _, st = new.load_round(4)
    np.testing.assert_array_equal(p[0], params[0])
    assert st["round"] == 4


def test_client_checkpoint_skip_if_done(tmp_path):
    store = FileStore(tmp_path)
    mgr = ClientCheckpointManager(store, "run1")
    meta, params = _params()
    for step in [128, 256, 384]:
        mgr.save(cid=3, step=step, params_meta=meta, params=params,
                 extra_state={"loader": {"epoch": 0, "sample_in_epoch": step}})
    assert mgr.steps(3) == [128, 256, 384]
    assert mgr.latest_at_most(3, 300) == 256
    assert mgr.latest_at_most(3, 100) is None
    assert mgr.should_skip_round(3, 384)
    assert not mgr.should_skip_round(3, 512)

    _, p, opt, state = mgr.load(3, 256)
    assert opt is None
    assert state["loader"]["sample_in_epoch"] == 256

    assert mgr.cleanup(3, keep=1) == [128, 256]
    assert mgr.steps(3) == [384]


def test_trainer_opt_state_roundtrip(tiny_trainer):
    """Full TrainState round-trip through the checkpoint arrays path."""
    trainer, batch = tiny_trainer
    trainer.fit([batch, batch], duration_steps=2)
    om, oa = trainer.get_opt_state_arrays()
    pm, pa = trainer.get_parameters()
    step = trainer.step

    trainer2_m, trainer2_a = trainer.get_opt_state_arrays()
    trainer.reset_optimizer()
    changed = any(
        not np.array_equal(x, y)
        for x, y in zip(oa, trainer.get_opt_state_arrays()[1])
    )
    assert changed  # moments were non-zero after 2 steps

    trainer.set_opt_state_arrays(om, oa)
    trainer.set_parameters(pm, pa)
    trainer.set_step(step)
    for x, y in zip(oa, trainer.get_opt_state_arrays()[1]):
        np.testing.assert_allclose(x, y, rtol=1e-6)
    del trainer2_m, trainer2_a
