"""Wire-compression subsystem (``photon_tpu/compression``).

Acceptance oracles (ISSUE 1): round-trip exactness for delta-only mode,
bounded quantization error for q8, error-feedback residual accounting, and a
small end-to-end federated run (inline transport) where delta+topk+q8
aggregates to within 1e-2 of the uncompressed FedAvg result after 3 rounds
while reporting ≥4× bytes-on-wire reduction on the uplink.
"""

import numpy as np
import pytest

from photon_tpu.codec import ParamsMetadata
from photon_tpu.compression import (
    Codec,
    CompressedPayload,
    decode_payload,
    dequantize_q8,
    make_codec,
    quantize_q8,
    topk_sparsify,
)
from photon_tpu.config.schema import CompressionConfig
from photon_tpu.federation.transport import ParamTransport


def _payload_fixture(seed=0, scale=0.02, delta_scale=1e-3):
    rng = np.random.default_rng(seed)
    arrays = [
        rng.normal(0, scale, (64, 32)).astype(np.float32),
        rng.normal(0, scale, (33,)).astype(np.float32),  # non-multiple of q8 block
        rng.normal(0, scale, (7,)).astype(np.float32),  # smaller than any block
    ]
    ref = [a + rng.normal(0, delta_scale, a.shape).astype(np.float32) for a in arrays]
    meta = ParamsMetadata.from_ndarrays(["w", "v", "b"], arrays)
    return meta, arrays, ref


# ---------------------------------------------------------------------------
# stages
# ---------------------------------------------------------------------------


def test_quantize_q8_bounded_error():
    rng = np.random.default_rng(3)
    x = rng.normal(0, 1.0, 10_000).astype(np.float32)
    codes, scales = quantize_q8(x, block=256)
    assert codes.dtype == np.int8 and scales.dtype == np.float32
    back = dequantize_q8(codes, scales, block=256)
    # per-block bound: absmax/254 (half the quantization step)
    grid = np.zeros(-(-x.size // 256) * 256, np.float32)
    grid[: x.size] = x
    bounds = np.repeat(np.abs(grid.reshape(-1, 256)).max(axis=1) / 254, 256)[: x.size]
    assert np.all(np.abs(x - back) <= bounds + 1e-7)


def test_quantize_q8_zero_block_exact():
    x = np.zeros(300, np.float32)
    codes, scales = quantize_q8(x, block=256)
    assert np.array_equal(dequantize_q8(codes, scales, block=256), x)


def test_topk_keeps_largest_magnitudes():
    x = np.array([0.1, -5.0, 0.01, 3.0, -0.2, 0.0, 2.0, -1.0], np.float64)
    idx, vals = topk_sparsify(x, ratio=0.5)
    assert list(idx) == [1, 3, 6, 7]  # sorted indices of the top-4 |x|
    assert np.array_equal(vals, x[[1, 3, 6, 7]])


# ---------------------------------------------------------------------------
# codec policies
# ---------------------------------------------------------------------------


def test_delta_only_roundtrip_exact():
    """The delta-only policy is lossless: float64 deltas of float32 arrays
    reconstruct bit-for-bit."""
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta")
    codec.set_reference(ref)
    payload = codec.encode(meta, arrays, key=0)
    assert payload.has_delta
    out = codec.decode(payload)
    for a, o in zip(arrays, out):
        assert o.dtype == a.dtype
        assert np.array_equal(a, o)
    # and lossless means the EF residual is exactly zero
    assert codec.ef.residual_norm(0) == 0.0


def test_q8_policy_bounded_error():
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta_q8")
    codec.set_reference(ref)
    out = codec.decode(codec.encode(meta, arrays, key=None))
    for a, r, o in zip(arrays, ref, out):
        bound = np.abs(np.asarray(a, np.float64) - np.asarray(r, np.float64)).max() / 254
        assert np.abs(np.asarray(a, np.float64) - o).max() <= bound + 1e-9


def test_topk_q8_hits_4x_wire_reduction():
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta_topk_q8", topk_ratio=0.125)
    codec.set_reference(ref)
    payload = codec.encode(meta, arrays, key=None)
    assert payload.raw_nbytes == meta.total_bytes
    assert payload.compression_ratio >= 4.0


def test_encode_without_reference_falls_back_to_values():
    """No broadcast yet → has_delta=False, values encode against zero —
    legal for dense policies, REFUSED for top-k (which would zero most of
    the absolute weights silently)."""
    meta, arrays, _ = _payload_fixture()
    codec = Codec("delta")
    payload = codec.encode(meta, arrays, key=None)
    assert not payload.has_delta
    out = decode_payload(payload, reference=None)
    assert all(np.array_equal(a, o) for a, o in zip(arrays, out))

    topk_codec = Codec("delta_topk_q8")
    with pytest.raises(RuntimeError, match="delta reference"):
        topk_codec.encode(meta, arrays, key=None)


def test_error_feedback_lru_cap():
    """One residual is a full fp32 model copy — the store is LRU-bounded."""
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta_q8", ef_max_clients=2)
    codec.set_reference(ref)
    for cid in (0, 1, 2):
        codec.encode(meta, arrays, key=cid)
    assert codec.ef.residual(0) is None  # evicted (least recently used)
    assert codec.ef.residual(1) is not None and codec.ef.residual(2) is not None
    # lossless policies never store residuals at all
    lossless = Codec("delta")
    lossless.set_reference(ref)
    lossless.encode(meta, arrays, key=0)
    assert lossless.ef.residual(0) is None


def test_error_feedback_residual_accounting():
    """residual_t = (delta_t + residual_{t-1}) − decode(encode(...)), per
    layer — checked against a by-hand recomputation over two rounds."""
    meta, arrays, ref = _payload_fixture(delta_scale=5e-3)
    codec = Codec("delta_topk_q8", topk_ratio=0.25)
    codec.set_reference(ref)

    deltas = [(np.asarray(a, np.float64) - np.asarray(r, np.float64)).reshape(-1)
              for a, r in zip(arrays, ref)]

    def decoded_deltas(c, payload):
        return [(np.asarray(o, np.float64) - np.asarray(r, np.float64)).reshape(-1)
                for o, r in zip(c.decode(payload), ref)]

    payload1 = codec.encode(meta, arrays, key=7)
    decoded1 = decoded_deltas(codec, payload1)
    res1 = codec.ef.residual(7)
    for d, dec, r in zip(deltas, decoded1, res1):
        np.testing.assert_allclose(r, d - dec, atol=1e-6)

    # round 2, same raw delta: the encoder sees delta + residual
    payload2 = codec.encode(meta, arrays, key=7)
    decoded2 = decoded_deltas(codec, payload2)
    res2 = codec.ef.residual(7)
    for d, r1, dec2, r2 in zip(deltas, res1, decoded2, res2):
        np.testing.assert_allclose(r2, (d + r1) - dec2, atol=1e-6)

    # EF means the two rounds together deliver more of the true mass than
    # two independent lossy encodes would: total decoded ≈ 2·delta + o(1)
    err_with_ef = sum(
        float(np.abs(2 * d - (a + b)).sum())
        for d, a, b in zip(deltas, decoded1, decoded2)
    )
    codec_no_ef = Codec("delta_topk_q8", topk_ratio=0.25, error_feedback=False)
    codec_no_ef.set_reference(ref)
    dec_no_ef = decoded_deltas(codec_no_ef, codec_no_ef.encode(meta, arrays))
    err_no_ef = sum(
        float(np.abs(2 * d - 2 * a).sum()) for d, a in zip(deltas, dec_no_ef)
    )
    assert err_with_ef < err_no_ef


def test_stale_residual_dropped_on_shape_change():
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta_q8")
    codec.set_reference(ref)
    codec.encode(meta, arrays, key=1)
    assert codec.ef.residual(1) is not None
    # same key, different payload layout (momenta toggled, say)
    meta2 = ParamsMetadata.from_ndarrays(["w"], [arrays[0]])
    codec.set_reference([ref[0]])
    codec.encode(meta2, [arrays[0]], key=1)
    assert len(codec.ef.residual(1)) == 1


# ---------------------------------------------------------------------------
# container
# ---------------------------------------------------------------------------


def test_payload_container_roundtrip_and_versioning():
    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta_topk_q8", topk_ratio=0.25)
    codec.set_reference(ref)
    payload = codec.encode(meta, arrays, key=None)
    data = payload.to_bytes()
    back = CompressedPayload.from_bytes(data)
    assert back.policy == payload.policy
    assert back.has_delta == payload.has_delta
    assert [b.name for b in back.layers] == [b.name for b in payload.layers]
    assert all(np.array_equal(a, o)
               for a, o in zip(codec.decode(payload), codec.decode(back)))
    with pytest.raises(ValueError, match="magic"):
        CompressedPayload.from_bytes(b"XXXX" + data[4:])
    with pytest.raises(ValueError, match="version"):
        CompressedPayload.from_bytes(data[:4] + b"\x63\x00" + data[6:])
    with pytest.raises(ValueError, match="trailing"):
        CompressedPayload.from_bytes(data + b"\x00")


def test_make_codec_from_config():
    assert make_codec(None) is None
    assert make_codec("off") is None
    assert make_codec(CompressionConfig()) is None  # default policy off
    codec = make_codec(CompressionConfig(policy="delta_topk_q8", topk_ratio=0.5,
                                         q8_block_size=128, error_feedback=False))
    assert codec.policy == "delta_topk_q8"
    assert codec.topk_ratio == 0.5 and codec.q8_block == 128 and codec.ef is None


def test_compression_config_validated():
    from photon_tpu.config.schema import Config

    cfg = Config()
    cfg.photon.compression.policy = "gzip"
    with pytest.raises(ValueError, match="policy"):
        cfg.validate()
    cfg.photon.compression.policy = "delta_q8"
    cfg.photon.compression.topk_ratio = 0.0
    with pytest.raises(ValueError, match="topk_ratio"):
        cfg.validate()
    cfg.photon.compression.topk_ratio = 0.125
    cfg.photon.compression.q8_block_size = 0
    with pytest.raises(ValueError, match="q8_block_size"):
        cfg.validate()


# ---------------------------------------------------------------------------
# transport integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["inline", "shm"])
def test_transport_compressed_roundtrip(mode):
    meta, arrays, ref = _payload_fixture()
    tr = ParamTransport(mode, compression=CompressionConfig(policy="delta_topk_q8",
                                                            topk_ratio=0.125))
    try:
        tr.set_reference(ref)
        ptr = tr.put("cmp-test", meta, arrays, compress=True, key=3)
        info = ptr.codec_info()
        assert info is not None and info["policy"] == "delta_topk_q8"
        # metadata_json keeps the ORIGINAL contract (back-compatible field)
        assert ParamsMetadata.from_json(ptr.metadata_json).names == meta.names

        got_meta, out = tr.get(ptr)
        got_meta.validate_arrays(out)
        for a, r, o in zip(arrays, ref, out):
            assert np.abs(a - o).max() <= np.abs(a - r).max() + 1e-7

        # decode=False hands back the still-compressed payload (the O(1)
        # streaming-aggregation path)
        _, payload = tr.get(ptr, decode=False)
        assert isinstance(payload, CompressedPayload)
        assert payload.compression_ratio >= 4.0
        assert tr.stats.recv_wire_bytes < tr.stats.recv_raw_bytes / 4

        # raw pointers still work through the same transport
        raw_ptr = tr.put("raw-test", meta, arrays)
        assert raw_ptr.codec_info() is None
        _, raw_out = tr.get(raw_ptr)
        assert all(np.array_equal(a, o) for a, o in zip(arrays, raw_out))
    finally:
        tr.cleanup()


def test_transport_without_codec_rejects_compressed_pointer():
    meta, arrays, ref = _payload_fixture()
    sender = ParamTransport("inline", compression="delta_q8")
    sender.set_reference(ref)
    ptr = sender.put("x", meta, arrays, compress=True)
    receiver = ParamTransport("inline")
    with pytest.raises(RuntimeError, match="no codec"):
        receiver.get(ptr)


def test_aggregate_inplace_compressed_stream():
    from photon_tpu.strategy.aggregation import aggregate_inplace

    meta, arrays, ref = _payload_fixture()
    codec = Codec("delta", error_feedback=False)  # lossless → exact equality
    codec.set_reference(ref)
    clients = [
        ([a + 0.01 * i for a in arrays], 10 * (i + 1)) for i in range(3)
    ]
    plain = aggregate_inplace(iter(clients))
    compressed = aggregate_inplace(
        iter([(codec.encode(meta, [np.float32(a) for a in arrs]), n)
              for arrs, n in clients]),
        decode=codec.decode,
    )
    assert plain[1] == compressed[1]
    for a, b in zip(plain[0], compressed[0]):
        np.testing.assert_allclose(a, b, atol=1e-6)
    # without a decode hook, a payload stream is a loud error
    with pytest.raises(TypeError, match="decode"):
        aggregate_inplace(iter([(codec.encode(meta, arrays), 1)]))
    # a payload with a different array count must not fold partially
    with pytest.raises(ValueError, match="accumulator"):
        aggregate_inplace(iter([(arrays, 1), (arrays[:1], 1)]))


# ---------------------------------------------------------------------------
# end-to-end federated parity (inline transport)
# ---------------------------------------------------------------------------


def _fed_cfg(tmp_path, policy):
    from photon_tpu.config.schema import (
        Config,
        FLConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        PhotonConfig,
        SchedulerConfig,
        TrainConfig,
    )

    cfg = Config(
        run_uuid="cmp-e2e",
        model=ModelConfig(
            d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
            attn_impl="xla", compute_dtype="float32",
        ),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=1000),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4, eval_batches=2),
        fl=FLConfig(
            n_total_clients=4, n_clients_per_round=2, n_rounds=3, local_steps=2,
            strategy_name="fedavg", server_learning_rate=1.0, sample_seed=99,
        ),
        photon=PhotonConfig(save_path=str(tmp_path / "save"), checkpoint=False),
    )
    cfg.dataset.synthetic = True
    cfg.photon.compression.policy = policy
    cfg.photon.compression.topk_ratio = 0.125
    return cfg.validate()


def _run_fed(cfg):
    from photon_tpu.federation import InProcessDriver, NodeAgent, ServerApp

    comp = cfg.photon.compression
    transport = ParamTransport("inline", compression=comp)

    def make_agent(node_id):
        return NodeAgent(cfg, node_id,
                         lambda: ParamTransport("inline", compression=comp))

    driver = InProcessDriver(cfg, make_agent, n_nodes=2)
    app = ServerApp(cfg, driver, transport)
    history = app.run()
    params = [a.copy() for a in app.strategy.current_parameters]
    app.driver.shutdown()
    return params, history


def test_e2e_compressed_fedavg_matches_uncompressed(tmp_path):
    """delta+topk+q8 with error feedback stays within 1e-2 of the
    uncompressed FedAvg parameters after 3 rounds, at ≥4× less uplink."""
    p_raw, _ = _run_fed(_fed_cfg(tmp_path / "raw", "off"))
    p_cmp, hist = _run_fed(_fed_cfg(tmp_path / "cmp", "delta_topk_q8"))

    diff = max(float(np.abs(a - b).max()) for a, b in zip(p_raw, p_cmp))
    assert diff < 1e-2, f"compressed run diverged: max param diff {diff}"

    ratio = hist.latest("server/wire_compression_ratio")
    assert ratio is not None and ratio >= 4.0, f"uplink ratio {ratio}"
    assert len(hist.series("server/wire_uplink_bytes")) == 3
    # run-level accounting via the History counter helper
    assert hist.cumulative("server/wire_uplink_bytes") * 4 <= hist.cumulative(
        "server/wire_uplink_raw_bytes"
    )


# ---------------------------------------------------------------------------
# in-collective jnp port (ISSUE 7): single source of truth with quantize.py
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n,block",
    [
        (1024, 256),   # aligned
        (1000, 256),   # ragged final block
        (33, 16),      # ragged, small
        (5, 256),      # single partial block
        (256, 256),    # exactly one block
    ],
    ids=["aligned", "ragged", "ragged-small", "partial", "one-block"],
)
def test_quantize_jnp_port_golden_parity(n, block):
    """The jnp port used INSIDE the cross-slice collective must produce
    byte-identical int8 codes and fp32 scales to the host codec — the
    aggregation plane's error analysis is only valid if the two quantizers
    ARE the same quantizer."""
    from photon_tpu.compression.quantize_jnp import (
        dequantize_q8_jnp,
        quantize_q8_jnp,
    )

    rng = np.random.default_rng(n * 31 + block)
    x = rng.normal(0, 0.7, n).astype(np.float32)
    # exercise the all-zero-block guard (scale 0, codes 0) when possible
    if n >= 2 * block:
        x[block : 2 * block] = 0.0

    codes_np, scales_np = quantize_q8(x, block=block)
    codes_j, scales_j = quantize_q8_jnp(x, block=block)
    np.testing.assert_array_equal(codes_np, np.asarray(codes_j))
    np.testing.assert_array_equal(scales_np, np.asarray(scales_j))
    assert np.asarray(codes_j).dtype == np.int8
    assert np.asarray(scales_j).dtype == np.float32

    back_np = dequantize_q8(codes_np, scales_np, block=block)
    back_j = dequantize_q8_jnp(codes_j, scales_j, block=block)
    np.testing.assert_array_equal(back_np, np.asarray(back_j))


def test_quantize_jnp_port_all_zero_input():
    from photon_tpu.compression.quantize_jnp import (
        dequantize_q8_jnp,
        quantize_q8_jnp,
    )

    x = np.zeros(100, np.float32)
    codes_np, scales_np = quantize_q8(x, block=32)
    codes_j, scales_j = quantize_q8_jnp(x, block=32)
    np.testing.assert_array_equal(codes_np, np.asarray(codes_j))
    np.testing.assert_array_equal(scales_np, np.asarray(scales_j))
    np.testing.assert_array_equal(np.asarray(dequantize_q8_jnp(codes_j, scales_j, block=32)), x)


def test_quantizer_constants_single_source():
    """DEFAULT_BLOCK/_QMAX are imported by the jnp port, never redeclared."""
    import photon_tpu.compression.quantize as qnp
    import photon_tpu.compression.quantize_jnp as qj

    assert qj.DEFAULT_BLOCK is qnp.DEFAULT_BLOCK
    assert qj._QMAX is qnp._QMAX


# ---------------------------------------------------------------------------
# aligned-path micro-fix (ISSUE 7 satellite): no padded copy when
# n % block == 0, output identical to the reference padded implementation
# ---------------------------------------------------------------------------


def _quantize_q8_reference(values, block):
    """The pre-fix implementation: always pads (the oracle for the
    aligned-fast-path regression)."""
    flat = np.asarray(values, dtype=np.float32).reshape(-1)
    n = flat.size
    n_blocks = max(1, -(-n // block))
    padded = np.zeros(n_blocks * block, dtype=np.float32)
    padded[:n] = flat
    grid = padded.reshape(n_blocks, block)
    absmax = np.abs(grid).max(axis=1)
    scales = (absmax / 127.0).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0)[:, None]
    codes = np.clip(np.rint(grid / safe), -127.0, 127.0).astype(np.int8)
    return codes.reshape(-1)[:n].copy(), scales


@pytest.mark.parametrize("n,block", [(512, 256), (256, 256), (64, 16), (1000, 256), (0, 256)],
                         ids=["aligned-2", "aligned-1", "aligned-small", "ragged", "empty"])
def test_quantize_q8_aligned_fast_path_identical(n, block):
    rng = np.random.default_rng(7)
    x = rng.normal(0, 1.0, n).astype(np.float32)
    codes, scales = quantize_q8(x, block=block)
    ref_codes, ref_scales = _quantize_q8_reference(x, block=block)
    np.testing.assert_array_equal(codes, ref_codes)
    np.testing.assert_array_equal(scales, ref_scales)
    back = dequantize_q8(codes, scales, block=block)
    padded = np.zeros(max(1, -(-n // block)) * block, np.float32)
    padded[:n] = ref_codes.astype(np.float32)
    ref_back = (padded.reshape(-1, block) * ref_scales[:, None]).reshape(-1)[:n]
    np.testing.assert_array_equal(back, ref_back)


def test_quantize_q8_aligned_returns_fresh_arrays():
    """The fast path must not alias the caller's buffer (the wire encoder
    mutates inputs downstream)."""
    x = np.linspace(-1, 1, 512, dtype=np.float32)
    codes, _ = quantize_q8(x, block=256)
    assert not np.shares_memory(codes, x)
    codes[0] += 1  # writable, independently owned
    back = dequantize_q8(codes, np.ones(2, np.float32), block=256)
    assert not np.shares_memory(back, codes)
