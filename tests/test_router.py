"""Fleet router (ISSUE 16): affinity placement, control plane, failover.

Four layers of contract:

1. the placement policy in isolation — rendezvous stability (churn moves
   only the dead replica's keys), cohort pins stick and re-pin on death,
   prefix keys follow the ``serve/prefix.py`` chain property, p2c prefers
   the lower queue depth;
2. the load signal — ``ContinuousBatcher.load_report`` is cheap and
   truthful, and ``/healthz`` serves it;
3. routed == single-engine: greedy completions through a 3-replica fleet
   are BIT-EXACT against the offline contiguous decoder (routing changes
   placement, never outputs);
4. failover — SIGKILL-shaped replica death walks the liveness ladder,
   re-pins cohorts, degrades the fleet health plane, and drops zero
   requests on survivors; the seeded chaos injector reproduces the same
   death mid-traffic (`chaos` marker).
"""

import http.client
import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import Config
from photon_tpu.serve.router import (
    AffinityRouter,
    NoReplicasError,
    ReplicaState,
    rendezvous_pick,
)


def _fleet_cfg(*, replicas=3, n_slots=2, block_size=4, max_seq=32,
               max_new=8, prefix_blocks=2) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 4
    cfg.model.vocab_size = 96
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.max_seq_len = max_seq
    cfg.photon.serve.n_slots = n_slots
    cfg.photon.serve.block_size = block_size
    cfg.photon.serve.max_new_tokens = max_new
    flt = cfg.photon.serve.fleet
    flt.enabled = True
    flt.replicas = replicas
    flt.prefix_affinity_blocks = prefix_blocks
    flt.report_poll_s = 0.1
    flt.report_timeout_s = 1.0
    return cfg.validate()


def _params(cfg):
    from photon_tpu.models.mpt import init_params

    return init_params(cfg.model, seed=4)


def _post_generate(port, payload, timeout=60):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        c.request("POST", "/generate", body=json.dumps(payload).encode(),
                  headers={"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, json.loads(r.read())
    finally:
        c.close()


def _offline_greedy(cfg, params, prompt, n):
    """Oracle: the contiguous cached decoder, one row (test_serve idiom)."""
    from photon_tpu.models.decode import make_cached_generate_fn

    buf = np.zeros((1, len(prompt) + n), np.int32)
    buf[0, : len(prompt)] = prompt
    fn = make_cached_generate_fn(cfg.model, params)
    t, _ = fn.many(jnp.asarray(buf), jnp.asarray([len(prompt)], np.int32), n)
    return [int(x) for x in np.asarray(t)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# 1. placement policy in isolation
# ---------------------------------------------------------------------------


def test_rendezvous_stable_and_minimal_churn():
    live = [f"r{i}" for i in range(5)]
    keys = [f"key{i}".encode() for i in range(64)]
    before = {k: rendezvous_pick(k, live) for k in keys}
    # deterministic: same inputs, same winners
    assert before == {k: rendezvous_pick(k, live) for k in keys}
    # removing one replica moves ONLY the keys that lived on it
    dead = "r2"
    shrunk = [r for r in live if r != dead]
    for k, old in before.items():
        new = rendezvous_pick(k, shrunk)
        if old != dead:
            assert new == old, "churn moved a key off a surviving replica"
        else:
            assert new in shrunk
    with pytest.raises(NoReplicasError):
        rendezvous_pick(b"x", [])


def test_p2c_prefers_lower_queue_depth():
    r = AffinityRouter(block_size=4, prefix_affinity_blocks=0)
    loads = {
        "a": ReplicaState("a", queue_depth=7, live_slot_frac=1.0),
        "b": ReplicaState("b", queue_depth=0, live_slot_frac=0.0),
    }
    for _ in range(16):
        rid, reason = r.route([1, 2], None, ["a", "b"], loads)
        assert (rid, reason) == ("b", "p2c")


def test_cohort_pin_sticks_and_repins_on_death():
    r = AffinityRouter(block_size=4)
    live = ["r0", "r1", "r2"]
    first, reason = r.route([1] * 16, "tenant-a", live, {})
    assert reason == "cohort"
    for _ in range(8):
        assert r.route([9] * 16, "tenant-a", live, {})[0] == first
    # death: the pin moves to a survivor and sticks there
    survivors = [x for x in live if x != first]
    moved = r.repin_dead(first, survivors)
    assert moved and moved[0][0] == "tenant-a" and moved[0][1] in survivors
    assert r.route([1] * 16, "tenant-a", survivors, {})[0] == moved[0][1]
    # empty-string cohort is NOT a cohort (anonymous traffic)
    rid, reason = r.route([1, 2], "", live, {"r0": ReplicaState("r0")})
    assert reason == "p2c"


def test_prefix_key_follows_chain_property():
    r = AffinityRouter(block_size=4, prefix_affinity_blocks=2)
    assert r.prefix_key(None) is None
    assert r.prefix_key([1, 2, 3]) is None  # shorter than one block
    base = [7, 1, 2, 3, 9, 9, 9, 9]
    # same first prefix_affinity_blocks * block_size tokens -> same key,
    # regardless of the tail
    k1 = r.prefix_key(base + [5, 5, 5])
    k2 = r.prefix_key(base + [6, 6, 6, 6, 6])
    assert k1 == k2 and k1 is not None
    # a different first block -> a different key
    assert r.prefix_key([8] + base[1:]) != k1
    live = ["r0", "r1", "r2", "r3"]
    routed = {r.route(base + [i], None, live, {})[0] for i in range(8)}
    assert len(routed) == 1, "shared-prefix traffic must converge"
    assert r.route(base, None, live, {})[1] == "prefix"


def test_random_mode_bypasses_affinity():
    import random

    r = AffinityRouter(block_size=4, mode="random", rng=random.Random(0))
    live = ["r0", "r1", "r2", "r3"]
    picks = {r.route([1] * 16, "tenant-a", live, {})[0] for _ in range(64)}
    assert len(picks) > 1, "random mode must spread even cohort traffic"
    assert r.route([1] * 16, "tenant-a", live, {})[1] == "random"
    assert not r.pins


def test_fleet_config_validation():
    for attr, bad in [("replicas", 0), ("port", 70000), ("control_port", -1),
                      ("prefix_affinity_blocks", -1), ("report_poll_s", 0.0),
                      ("report_timeout_s", -1.0), ("route_retries", -1)]:
        cfg = Config()
        setattr(cfg.photon.serve.fleet, attr, bad)
        with pytest.raises(ValueError, match="fleet"):
            cfg.validate()
    assert Config().validate().photon.serve.fleet.replicas == 2


def test_registry_covers_router_names():
    from photon_tpu.utils.profiling import registered_metric_names

    names = registered_metric_names()
    for expect in ("router/requests_total", "router/routed_prefix_total",
                   "router/routed_cohort_total", "router/routed_p2c_total",
                   "router/reroutes_total", "router/proxy_errors_total",
                   "router/replicas_live", "router/replicas_dead",
                   "router/cohort_repins_total", "serve/fleet_replicas",
                   "serve/fleet_rolling_swaps_total"):
        assert expect in names, expect


# ---------------------------------------------------------------------------
# 2. load signal
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_fleet():
    """One 3-replica in-process fleet shared by the e2e tests below (the
    jax compile cache makes replicas 2..N cheap; one fixture keeps the
    module inside the tier-1 budget)."""
    from photon_tpu.serve.fleet import InProcessFleet

    cfg = _fleet_cfg()
    params = _params(cfg)
    fleet = InProcessFleet(cfg, params)
    port = fleet.start(timeout=60)
    yield cfg, params, fleet, port
    fleet.close()


def test_load_report_is_cheap_and_truthful(served_fleet):
    _, _, fleet, _ = served_fleet
    rep = fleet.replicas["replica0"]["batcher"].load_report()
    assert set(rep) == {"queue_depth", "live_slot_frac", "draining"}
    assert rep["queue_depth"] == 0
    assert 0.0 <= rep["live_slot_frac"] <= 1.0
    assert rep["draining"] is False


def test_replica_healthz_serves_load(served_fleet):
    _, _, fleet, _ = served_fleet
    fe = fleet.replicas["replica0"]["frontend"]
    c = http.client.HTTPConnection(fe.host, fe.port, timeout=10)
    try:
        c.request("GET", "/healthz")
        body = json.loads(c.getresponse().read())
    finally:
        c.close()
    assert body["load"]["queue_depth"] == 0
    assert body["load"]["draining"] is False


# ---------------------------------------------------------------------------
# 3. routing never changes outputs
# ---------------------------------------------------------------------------


def test_routed_greedy_bitexact_vs_single_engine(served_fleet):
    cfg, params, fleet, port = served_fleet
    rng = np.random.default_rng(7)
    shared = list(map(int, rng.integers(1, 96, 8)))  # 2 full routed blocks
    prompts = [shared + list(map(int, rng.integers(1, 96, rng.integers(2, 6))))
               for _ in range(5)]
    prompts.append(list(map(int, rng.integers(1, 96, 3))))  # p2c path
    for p in prompts:
        status, out = _post_generate(port, {"tokens": p, "max_new_tokens": 6})
        assert status == 200
        assert out["tokens"] == _offline_greedy(cfg, params, p, 6), p
    st = fleet.router.fleet_status()["fleet"]
    assert st["routed"]["requests"] >= len(prompts)
    assert st["routed"]["prefix"] >= 5  # the shared-prefix traffic


def test_fleet_status_and_metrics_planes(served_fleet):
    _, _, fleet, port = served_fleet
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        c.request("GET", "/healthz")
        body = json.loads(c.getresponse().read())
        assert body["fleet"]["live"] == 3 and body["fleet"]["dead"] == 0
        assert set(body["fleet"]["replicas"]) == {
            "replica0", "replica1", "replica2"}
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode()
    finally:
        c.close()
    assert "router_requests_total" in text or "router/requests_total" in text


def test_rolling_hotswap_one_replica_at_a_time(served_fleet):
    _, _, fleet, _ = served_fleet

    windows = {}
    lock = threading.Lock()

    class _FakeWatcher:
        def __init__(self, rid):
            self.rid = rid

        def poll_once(self):
            t0 = time.monotonic()
            time.sleep(0.05)
            with lock:
                windows[self.rid] = (t0, time.monotonic())
            return "swapped"

    for rid, rep in fleet.replicas.items():
        rep["agent"].watcher = _FakeWatcher(rid)
    try:
        results = fleet.router.rolling_hotswap(timeout_s=10)
    finally:
        for rep in fleet.replicas.values():
            rep["agent"].watcher = None
    assert len(results) == 3 and all(r["ok"] and r["swapped"] for r in results)
    # strictly one replica mid-swap at a time: windows never overlap
    spans = sorted(windows.values())
    for (_, end_a), (start_b, _) in zip(spans, spans[1:]):
        assert end_a <= start_b, "two replicas were mid-swap concurrently"
    assert fleet.router.rolling_swaps == 1


# ---------------------------------------------------------------------------
# 4. failover
# ---------------------------------------------------------------------------


def _wait_dead(router, rid, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rid not in router.live_replicas():
            h = router.tracker.nodes.get(rid)
            if h is not None and h.state == "dead":
                return
        time.sleep(0.05)
    raise AssertionError(f"{rid} never went dead on the router")


def test_replica_death_zero_drops_on_survivors():
    """SIGKILL-shaped death: the fleet degrades to 2/3, every subsequent
    request still completes (reroute on connect failure), membership +
    fleet events fire, cohort pins move off the corpse."""
    from photon_tpu import telemetry
    from photon_tpu.config.schema import TelemetryConfig
    from photon_tpu.serve.fleet import InProcessFleet

    cfg = _fleet_cfg()
    params = _params(cfg)
    telemetry.install(TelemetryConfig(enabled=True), scope="fleet-test")
    fleet = InProcessFleet(cfg, params)
    try:
        port = fleet.start(timeout=60)
        victim = "replica1"
        # pin a cohort onto the victim so death must re-pin it
        fleet.router.policy.pins["tenant-a"] = victim
        fleet.kill_replica(victim)
        _wait_dead(fleet.router, victim)
        ok = 0
        for i in range(6):
            status, out = _post_generate(
                port, {"tokens": [1 + i, 2, 3, 4, 5], "max_new_tokens": 4})
            assert status == 200, f"request {i} dropped after replica death"
            assert len(out["tokens"]) == 4
            ok += 1
        assert ok == 6
        st = fleet.router.fleet_status()["fleet"]
        assert st["dead"] == 1 and st["live"] == 2
        assert st["pins"].get("tenant-a") != victim
        events = telemetry.drain_events()
        kinds = [e["kind"] for e in events]
        assert "membership/transition" in kinds
        assert "fleet/replica_dead" in kinds
        assert "fleet/cohort_repin" in kinds
        dead_ev = next(e for e in events if e["kind"] == "fleet/replica_dead")
        assert dead_ev["attrs"]["replica"] == victim
        h = telemetry.health_active()
        assert h is not None
        alerts = [a for a in h.alerts if a.kind == "alert/fleet_replica_dead"]
        assert alerts and alerts[0].attrs["replica"] == victim
    finally:
        fleet.close()
        telemetry.uninstall()


@pytest.mark.chaos
def test_chaos_replica_kill_mid_traffic():
    """Seeded FaultInjector kills one replica after N routed requests —
    deterministically, once — and the survivors drop nothing."""
    from photon_tpu import chaos, telemetry
    from photon_tpu.config.schema import ChaosConfig, TelemetryConfig
    from photon_tpu.serve.fleet import InProcessFleet

    cfg = _fleet_cfg()
    params = _params(cfg)
    telemetry.install(TelemetryConfig(enabled=True), scope="fleet-chaos")
    chaos.install(
        ChaosConfig(enabled=True, seed=77, replica_kill_after_requests=3),
        scope="fleet",
    )
    fleet = InProcessFleet(cfg, params)
    try:
        port = fleet.start(timeout=60)
        for i in range(8):
            status, out = _post_generate(
                port, {"tokens": [2 + i, 3, 4, 5, 6], "max_new_tokens": 4})
            assert status == 200, f"request {i} dropped around the kill"
            assert len(out["tokens"]) == 4
        inj = chaos.active()
        assert inj is not None and inj.counts["replica_kill"] == 1
        killed = [r for r, rep in fleet.replicas.items() if rep["killed"]]
        assert len(killed) == 1
        _wait_dead(fleet.router, killed[0])
        st = fleet.router.fleet_status()["fleet"]
        assert st["dead"] == 1 and st["live"] == 2
        kinds = [e["kind"] for e in telemetry.drain_events()]
        assert "chaos/replica_kill" in kinds
        assert "fleet/replica_dead" in kinds
    finally:
        fleet.close()
        chaos.uninstall()
        telemetry.uninstall()
