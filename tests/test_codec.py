import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.codec import (
    ParamsMetadata,
    flatten_params,
    params_from_ndarrays,
    params_to_ndarrays,
    unflatten_params,
)


def _tree():
    return {
        "wte": {"embedding": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
        "blocks": {"block": {"wqkv": {"kernel": jnp.ones((3, 4), jnp.float32)}}},
        "ln_f": {"scale": jnp.zeros((3,), jnp.float32)},
    }


def test_flatten_deterministic_sorted():
    names, leaves = flatten_params(_tree())
    assert names == sorted(names)
    names2, _ = flatten_params(_tree())
    assert names == names2


def test_roundtrip():
    tree = _tree()
    meta, arrays = params_to_ndarrays(tree)
    assert meta.n_arrays == 3
    rebuilt = params_from_ndarrays(tree, meta, arrays)
    for a, b in zip(flatten_params(tree)[1], flatten_params(rebuilt)[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metadata_json_and_bounds():
    meta, arrays = params_to_ndarrays(_tree())
    meta2 = ParamsMetadata.from_json(meta.to_json())
    assert meta2 == meta
    assert meta.bounds[-1] == meta.total_bytes
    assert meta.total_bytes == sum(a.nbytes for a in arrays)


def test_validation_catches_shape_mismatch():
    tree = _tree()
    meta, arrays = params_to_ndarrays(tree)
    bad = list(arrays)
    bad[0] = np.zeros((5, 5), np.float32)
    with pytest.raises(ValueError):
        params_from_ndarrays(tree, meta, bad)


def test_unflatten_preserves_structure():
    tree = _tree()
    _, leaves = flatten_params(tree)
    rebuilt = unflatten_params(tree, [np.asarray(l) * 2 for l in leaves])
    names, new_leaves = flatten_params(rebuilt)
    for old, new in zip(leaves, new_leaves):
        np.testing.assert_allclose(np.asarray(old) * 2, np.asarray(new))
