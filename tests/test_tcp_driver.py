"""TCP driver tests: framing, registration/wait, a full fed round over
localhost sockets with node agents on threads, dead-node synthesis."""

import socket
import threading
import time
import zlib

import numpy as np
import pytest

from photon_tpu.federation import NodeAgent, ParamTransport, ServerApp
from photon_tpu.federation.messages import Ack, Envelope, Query
from photon_tpu.federation.tcp import (
    _FRAME,
    HELLO_KIND,
    CorruptFrameError,
    SocketConn,
    TcpServerDriver,
)
from tests.test_federation import make_cfg

pytestmark = pytest.mark.slow


def _thread_node(cfg, node_id, port):
    """Node agent on a thread (cheaper than a process; same socket path)."""

    def run():
        agent = NodeAgent(cfg, node_id, lambda: ParamTransport("inline"))
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        conn = SocketConn(sock)
        conn.send({"kind": HELLO_KIND, "node_id": node_id})
        try:
            agent.serve(conn)
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_socket_framing_roundtrip():
    a, b = socket.socketpair()
    ca, cb = SocketConn(a), SocketConn(b)
    payload = {"x": np.arange(5), "s": "hi"}
    ca.send(payload)
    got = cb.recv()
    np.testing.assert_array_equal(got["x"], payload["x"])
    ca.close(); cb.close()


def test_wait_for_nodes_times_out():
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    with pytest.raises(TimeoutError):
        driver.wait_for_nodes(timeout=0.3)
    driver.shutdown()


def test_recv_deadline_defeats_slow_drip():
    """The HELLO deadline is absolute, not per-recv: a peer dripping one
    byte per interval resets a plain settimeout forever but must still trip
    the deadline (otherwise it monopolizes the accept loop indefinitely)."""
    a, b = socket.socketpair()
    conn = SocketConn(a)
    conn.deadline = time.monotonic() + 0.4
    stop = threading.Event()

    def drip():
        # a plausible 64-byte frame header, then one byte at a time
        b.sendall(b"\x40" + b"\x00" * 11)
        while not stop.is_set():
            try:
                b.sendall(b"x")
            except OSError:
                return
            time.sleep(0.05)

    t = threading.Thread(target=drip, name="drip", daemon=True)
    t.start()
    start = time.monotonic()
    try:
        with pytest.raises(socket.timeout):
            conn.recv()
        assert time.monotonic() - start < 5.0
    finally:
        stop.set()
        conn.close()
        b.close()
        t.join(timeout=5)


def test_malformed_hello_does_not_kill_accept_loop():
    """A version-skewed client's HELLO missing node_id (or carrying garbage
    stats) must drop that one connection — never KeyError the accept thread
    to death, which would silently stop ALL future registrations."""
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    try:
        for bad in (
            {"kind": HELLO_KIND},  # no node_id
            "not even a dict",
            {"kind": HELLO_KIND, "node_id": "n9", "reconnects": "garbage"},
        ):
            sock = socket.create_connection(("127.0.0.1", driver.port), timeout=10)
            conn = SocketConn(sock)
            conn.send(bad)
            if isinstance(bad, dict) and bad.get("node_id") is None:
                # rejected HELLOs get their socket closed server-side
                sock.settimeout(5)
                with pytest.raises((EOFError, OSError)):
                    conn.recv()
            conn.close()
        # the accept thread survived: a well-formed node still registers
        sock = socket.create_connection(("127.0.0.1", driver.port), timeout=10)
        good = SocketConn(sock)
        good.send({"kind": HELLO_KIND, "node_id": "n1"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "n1" not in driver.node_ids():
            time.sleep(0.05)
        # n9's HELLO was well-formed apart from its stats: it registers with
        # the stats coerced to zero; n1 proves the accept loop is still alive
        assert set(driver.node_ids()) == {"n1", "n9"}
        assert driver.hello_stats()["n9"] == {"reconnects": 0, "backoff_s": 0.0}
        good.close()
    finally:
        driver.shutdown()


#: protocol-0 pickle whose GLOBAL opcode references a missing module —
#: pickle.loads raises ModuleNotFoundError, NOT UnpicklingError, which is
#: exactly what a version-skewed peer's renamed class produces
_UNPICKLABLE = b"cnosuchmodule_photon\nNoSuchCls\n."


def test_unpicklable_frame_is_corrupt_frame_error():
    """A CRC-valid but undecodable frame must surface as CorruptFrameError
    (an EOFError: every caller already tears the connection down on it),
    never leak ModuleNotFoundError into recv callers."""
    a, b = socket.socketpair()
    ca, cb = SocketConn(a), SocketConn(b)
    a.sendall(_FRAME.pack(len(_UNPICKLABLE), zlib.crc32(_UNPICKLABLE)) + _UNPICKLABLE)
    with pytest.raises(CorruptFrameError):
        cb.recv()
    ca.close(); cb.close()


def test_unpicklable_hello_does_not_kill_accept_loop():
    """The accept loop's HELLO catch is (EOFError, OSError): an unpicklable
    HELLO must arrive as CorruptFrameError and drop one connection, not kill
    the accept thread and silently stop all future registrations."""
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    try:
        sock = socket.create_connection(("127.0.0.1", driver.port), timeout=10)
        sock.sendall(_FRAME.pack(len(_UNPICKLABLE), zlib.crc32(_UNPICKLABLE)) + _UNPICKLABLE)
        sock.settimeout(5)
        assert sock.recv(1) == b""  # server dropped the connection
        sock.close()
        # the accept thread survived: a well-formed node still registers
        good = SocketConn(socket.create_connection(("127.0.0.1", driver.port), timeout=10))
        good.send({"kind": HELLO_KIND, "node_id": "n1"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "n1" not in driver.node_ids():
            time.sleep(0.05)
        assert driver.node_ids() == ["n1"]
        good.close()
    finally:
        driver.shutdown()


def test_tcp_fed_round(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=1, n_total_clients=2, n_clients_per_round=2, local_steps=1)
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=2)
    threads = [_thread_node(cfg, f"node{i}", driver.port) for i in range(2)]
    driver.wait_for_nodes(timeout=30)
    assert driver.node_ids() == ["node0", "node1"]

    app = ServerApp(cfg, driver, ParamTransport("inline"))
    try:
        history = app.run()
        assert history.latest("server/n_clients") == 2.0
        assert history.latest("server/round_time") is not None
    finally:
        driver.shutdown()
    for t in threads:
        t.join(timeout=10)


@pytest.mark.chaos
def test_reconnect_dead_letters_inflight_promptly():
    """A re-HELLO that replaces a stale socket must (a) drain the old
    connection's in-flight mids as immediate dead-letter replies — not let
    the sliding window eat a full fit_timeout_s per orphan — and (b) keep
    the replacement registered even when the OLD socket's EOF is noticed
    later."""
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    sock1 = socket.create_connection(("127.0.0.1", driver.port))
    conn1 = SocketConn(sock1)
    conn1.send({"kind": HELLO_KIND, "node_id": "ghost"})
    driver.wait_for_nodes(timeout=10)
    mid1 = driver.send("ghost", Query("ping"))
    mid2 = driver.send("ghost", Query("ping"))

    # reconnect under the same id while both requests are in flight
    sock2 = socket.create_connection(("127.0.0.1", driver.port))
    conn2 = SocketConn(sock2)
    conn2.send({"kind": HELLO_KIND, "node_id": "ghost",
                "reconnects": 1, "backoff_s": 0.7})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if driver.hello_stats().get("ghost", {}).get("reconnects") == 1:
            break
        time.sleep(0.02)
    assert driver.hello_stats()["ghost"]["backoff_s"] == 0.7

    t0 = time.monotonic()
    replies = [driver.recv_any(timeout=5) for _ in range(2)]
    assert time.monotonic() - t0 < 2.0, "dead letters must drain without a timeout"
    assert {mid for _, mid, _ in replies} == {mid1, mid2}
    for _, _, reply in replies:
        assert not reply.ok and "node died" in reply.detail

    # the replacement is still the registered node...
    assert driver.node_ids() == ["ghost"]
    # ...and EOF on the OLD socket must not evict it or kill new requests
    conn1.close()
    mid3 = driver.send("ghost", Query("ping"))
    env = conn2.recv()
    assert env.msg_id == mid3
    conn2.send(Envelope(Ack(ok=True, node_id="ghost"), env.msg_id))
    nid, mid, reply = driver.recv_any(timeout=10)
    assert (nid, mid) == ("ghost", mid3) and reply.ok
    assert driver.node_ids() == ["ghost"]
    conn2.close()
    driver.shutdown()


@pytest.mark.chaos
def test_run_node_supervisor_redials_with_backoff(tmp_path):
    """Sever the node's socket server-side: the run_node supervisor must
    back off (injected sleep records the jittered delay), redial, and
    re-HELLO with its cumulative reconnect stats."""
    from photon_tpu.federation.tcp import run_node

    cfg = make_cfg(tmp_path, n_rounds=1, n_total_clients=1, n_clients_per_round=1)
    cfg.photon.membership.reconnect_backoff_base_s = 0.25
    cfg.photon.membership.reconnect_backoff_jitter = 0.25
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    delays: list[float] = []
    t = threading.Thread(
        target=run_node,
        args=(f"127.0.0.1:{driver.port}", "n0", cfg.to_json()),
        kwargs={"sleep": delays.append},
        daemon=True,
    )
    t.start()
    driver.wait_for_nodes(timeout=120)  # first dial (after trainer build)
    assert driver.hello_stats()["n0"]["reconnects"] == 0

    with driver._lock:
        stale = driver._nodes["n0"]
    stale.close()  # simulated connection loss
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if driver.hello_stats().get("n0", {}).get("reconnects") == 1:
            break
        time.sleep(0.05)
    stats = driver.hello_stats()["n0"]
    assert stats["reconnects"] == 1
    # exactly one backoff was taken, within the jitter envelope of base·2^0
    assert len(delays) == 1
    assert 0.25 * 0.75 <= delays[0] <= 0.25 * 1.25
    assert stats["backoff_s"] == pytest.approx(delays[0])

    # the reconnected agent still serves
    mid = driver.send("n0", Query("ping"))
    nid, gotmid, reply = driver.recv_any(timeout=10)
    assert (nid, gotmid) == ("n0", mid) and reply.ok
    driver.shutdown()
    t.join(timeout=15)
    assert not t.is_alive()


@pytest.mark.chaos
def test_run_node_redials_after_agent_loop_crash(tmp_path, monkeypatch):
    """ISSUE 8 satellite: a crash that ESCAPES the agent loop — a torn
    collective stage a hybrid runtime drives, reply-path pickling, anything
    that isn't per-message-handled — used to kill the supervisor outright,
    removing the node from the federation forever. It must instead be
    treated as a torn connection: back off once, redial, re-HELLO into the
    next round, and participate full-strength (never re-enter the torn
    gang's half-finished round)."""
    from photon_tpu.federation.tcp import run_node

    cfg = make_cfg(tmp_path, n_rounds=1, n_total_clients=1,
                   n_clients_per_round=1, local_steps=1)
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)

    real_serve = NodeAgent.serve
    crashes = []

    def crash_once(self, conn):
        if not crashes:
            crashes.append(1)
            raise RuntimeError("simulated crash inside a collective stage")
        return real_serve(self, conn)

    monkeypatch.setattr(NodeAgent, "serve", crash_once)
    delays: list[float] = []
    t = threading.Thread(
        target=run_node,
        args=(f"127.0.0.1:{driver.port}", "n0", cfg.to_json()),
        kwargs={"sleep": delays.append},
        daemon=True,
    )
    t.start()
    # connection 1: HELLO lands, then the loop crashes (non-OSError). The
    # supervisor must come back with reconnects=1 — not exit the thread.
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if driver.hello_stats().get("n0", {}).get("reconnects") == 1:
            break
        time.sleep(0.05)
    assert driver.hello_stats()["n0"]["reconnects"] == 1
    assert crashes == [1]
    assert len(delays) == 1  # exactly one backoff between crash and redial

    # the readmitted node serves the NEXT round full-strength: a whole fed
    # round runs over the re-dialed socket
    app = ServerApp(cfg, driver, ParamTransport("inline"))
    try:
        history = app.run()
        assert history.latest("server/n_clients") == 1.0
        assert history.latest("server/round_failed") in (None, 0.0)
    finally:
        driver.shutdown()
    t.join(timeout=15)
    assert not t.is_alive()


def test_tcp_dead_node_synthesizes_failure():
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    # raw fake node that registers then vanishes mid-request
    sock = socket.create_connection(("127.0.0.1", driver.port))
    conn = SocketConn(sock)
    conn.send({"kind": HELLO_KIND, "node_id": "ghost"})
    driver.wait_for_nodes(timeout=10)
    mid = driver.send("ghost", Query("ping"))
    conn.close()
    nid, got_mid, reply = driver.recv_any(timeout=10)
    assert (nid, got_mid) == ("ghost", mid)
    assert not reply.ok and "died" in reply.detail
    assert "ghost" not in driver.node_ids()
    driver.shutdown()
