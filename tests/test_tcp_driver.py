"""TCP driver tests: framing, registration/wait, a full fed round over
localhost sockets with node agents on threads, dead-node synthesis."""

import socket
import threading

import numpy as np
import pytest

from photon_tpu.federation import NodeAgent, ParamTransport, ServerApp
from photon_tpu.federation.messages import Query
from photon_tpu.federation.tcp import HELLO_KIND, SocketConn, TcpServerDriver
from tests.test_federation import make_cfg

pytestmark = pytest.mark.slow


def _thread_node(cfg, node_id, port):
    """Node agent on a thread (cheaper than a process; same socket path)."""

    def run():
        agent = NodeAgent(cfg, node_id, lambda: ParamTransport("inline"))
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        conn = SocketConn(sock)
        conn.send({"kind": HELLO_KIND, "node_id": node_id})
        try:
            agent.serve(conn)
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_socket_framing_roundtrip():
    a, b = socket.socketpair()
    ca, cb = SocketConn(a), SocketConn(b)
    payload = {"x": np.arange(5), "s": "hi"}
    ca.send(payload)
    got = cb.recv()
    np.testing.assert_array_equal(got["x"], payload["x"])
    ca.close(); cb.close()


def test_wait_for_nodes_times_out():
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    with pytest.raises(TimeoutError):
        driver.wait_for_nodes(timeout=0.3)
    driver.shutdown()


def test_tcp_fed_round(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=1, n_total_clients=2, n_clients_per_round=2, local_steps=1)
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=2)
    threads = [_thread_node(cfg, f"node{i}", driver.port) for i in range(2)]
    driver.wait_for_nodes(timeout=30)
    assert driver.node_ids() == ["node0", "node1"]

    app = ServerApp(cfg, driver, ParamTransport("inline"))
    try:
        history = app.run()
        assert history.latest("server/n_clients") == 2.0
        assert history.latest("server/round_time") is not None
    finally:
        driver.shutdown()
    for t in threads:
        t.join(timeout=10)


def test_tcp_dead_node_synthesizes_failure():
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=1)
    # raw fake node that registers then vanishes mid-request
    sock = socket.create_connection(("127.0.0.1", driver.port))
    conn = SocketConn(sock)
    conn.send({"kind": HELLO_KIND, "node_id": "ghost"})
    driver.wait_for_nodes(timeout=10)
    mid = driver.send("ghost", Query("ping"))
    conn.close()
    nid, got_mid, reply = driver.recv_any(timeout=10)
    assert (nid, got_mid) == ("ghost", mid)
    assert not reply.ok and "died" in reply.detail
    assert "ghost" not in driver.node_ids()
    driver.shutdown()
