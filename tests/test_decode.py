"""KV-cache decoding equals full-forward decoding, token for token.

The cache path (``models/decode.py``) re-implements the block math outside
flax to scan over the stacked params; these equivalence tests are the
contract that pins it to the training model across every family variant:
MPT with learned positions, MPT with ALiBi, and llama (RoPE + RMSNorm +
SwiGLU + GQA), with per-row prompt lengths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import Config

from tests._helpers import tiny_llama_config


def _mpt_cfg(alibi: bool) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 4
    cfg.model.max_seq_len = 24
    cfg.model.vocab_size = 96
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.alibi = alibi
    cfg.model.learned_pos_emb = not alibi
    return cfg.validate()


def _moe_cfg():
    cfg = _mpt_cfg(alibi=False)
    cfg.model.mlp = "moe"
    cfg.model.moe_num_experts = 4
    cfg.model.moe_top_k = 2
    # ample capacity: decode's per-token batches are tiny, and the
    # prefill-vs-decode parity assertion needs identical (drop-free) routing
    cfg.model.moe_capacity_factor = 4.0
    return cfg.validate()


def _configs():
    return [
        ("mpt-wpe", _mpt_cfg(alibi=False)),
        ("mpt-alibi", _mpt_cfg(alibi=True)),
        ("llama-gqa", tiny_llama_config(n_kv_heads=2)),
        ("mpt-moe", _moe_cfg()),
    ]


@pytest.mark.parametrize("name,cfg", _configs(), ids=[n for n, _ in _configs()])
def test_prefill_logits_match_full_forward(name, cfg):
    from photon_tpu.models.decode import prefill
    from photon_tpu.models.mpt import MPTModel, init_params

    params = init_params(cfg.model, seed=4)
    model = MPTModel(cfg.model)
    s = 16
    tokens = np.random.default_rng(0).integers(0, cfg.model.vocab_size,
                                               (3, s), dtype=np.int32)
    lengths = np.asarray([5, 16, 9], np.int32)

    full = np.asarray(model.apply({"params": params}, tokens))  # [B,S,V]
    want = np.stack([full[i, lengths[i] - 1] for i in range(3)])

    logits, state = prefill(params, jnp.asarray(tokens), jnp.asarray(lengths),
                            cfg.model)
    np.testing.assert_allclose(np.asarray(logits), want, atol=2e-4, rtol=2e-4)
    assert state.cache_k.shape == (
        2, 3, s, cfg.model.n_kv_heads or cfg.model.n_heads, cfg.model.d_head
    )


@pytest.mark.parametrize("name,cfg", _configs(), ids=[n for n, _ in _configs()])
def test_cached_generate_matches_full_forward(name, cfg):
    from photon_tpu.eval.icl import make_generate_fn
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import MPTModel, init_params

    params = init_params(cfg.model, seed=4)
    model = MPTModel(cfg.model)
    s, gen = 16, 6
    tokens = np.zeros((3, s), np.int32)
    rng = np.random.default_rng(1)
    lengths = np.asarray([4, 7, 10], np.int32)
    for i, ln in enumerate(lengths):
        tokens[i, :ln] = rng.integers(1, cfg.model.vocab_size, ln)

    oracle = make_generate_fn(
        lambda p, t: model.apply({"params": p}, t), params
    )
    t_o, c_o = jnp.asarray(tokens), jnp.asarray(lengths)
    for _ in range(gen):
        t_o, c_o = oracle(t_o, c_o)

    cached = make_cached_generate_fn(cfg.model, params)
    t_c, c_c = cached.many(jnp.asarray(tokens), jnp.asarray(lengths), gen)

    np.testing.assert_array_equal(np.asarray(t_o), np.asarray(t_c))
    np.testing.assert_array_equal(np.asarray(c_o), np.asarray(c_c))


def test_cached_generate_with_numpy_params():
    """npz-loaded checkpoints hand the decoder HOST numpy leaves; indexing
    those with traced token ids crashed once — keep the regression."""
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import init_params

    cfg = _mpt_cfg(alibi=False)
    params = jax.tree.map(np.asarray, init_params(cfg.model, seed=0))
    fn = make_cached_generate_fn(cfg.model, params)
    tokens = jnp.zeros((2, 12), jnp.int32).at[:, :3].set(5)
    t, l = fn.many(tokens, jnp.asarray([3, 3], jnp.int32), 4)
    assert int(l[0]) == 7 and np.asarray(t).shape == (2, 12)


def test_cached_one_step_signature_matches_oracle():
    """The wrapper's __call__ is the compatible one-step path (and raises
    helpfully when constructed without a model_apply)."""
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import MPTModel, init_params

    cfg = _mpt_cfg(alibi=False)
    params = init_params(cfg.model, seed=0)
    model = MPTModel(cfg.model)
    fn = make_cached_generate_fn(
        cfg.model, params, lambda p, t: model.apply({"params": p}, t)
    )
    tokens = jnp.zeros((2, 8), jnp.int32).at[:, 0].set(3)
    lengths = jnp.asarray([1, 1], jnp.int32)
    t2, l2 = fn(tokens, lengths)
    assert t2.shape == tokens.shape and int(l2[0]) == 2

    bare = make_cached_generate_fn(cfg.model, params)
    with pytest.raises(ValueError, match="model_apply"):
        bare(tokens, lengths)


def test_many_rejects_buffer_overflow():
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import init_params

    cfg = _mpt_cfg(alibi=False)
    fn = make_cached_generate_fn(cfg.model, init_params(cfg.model, seed=0))
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="decode overflow"):
        fn.many(tokens, jnp.asarray([6], jnp.int32), 4)


def test_generate_sampling_modes():
    """temperature=0 is exactly the greedy cached path; sampled tokens stay
    inside the top-k support; fixed seed reproduces."""
    from photon_tpu.models.decode import generate, make_cached_generate_fn, prefill
    from photon_tpu.models.mpt import init_params

    cfg = _mpt_cfg(alibi=False)
    params = init_params(cfg.model, seed=0)
    tokens = np.zeros((2, 16), np.int32)
    tokens[:, :3] = [[5, 9, 2], [7, 1, 4]]
    lengths = np.asarray([3, 3], np.int32)
    tj, lj = jnp.asarray(tokens), jnp.asarray(lengths)

    greedy, _ = generate(params, tj, lj, cfg.model, 5, temperature=0.0)
    fn = make_cached_generate_fn(cfg.model, params)
    oracle, _ = fn.many(tj, lj, 5)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(oracle))

    s1, _ = generate(params, tj, lj, cfg.model, 5, temperature=1.0, seed=7)
    s2, _ = generate(params, tj, lj, cfg.model, 5, temperature=1.0, seed=7)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))  # same seed

    # top_k=1 collapses sampling back to greedy regardless of temperature
    k1, _ = generate(params, tj, lj, cfg.model, 5, temperature=2.0, top_k=1, seed=3)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))

    # top_k support: the first sampled token must be among the top-k logits
    k = 4
    logits, _ = prefill(params, tj, lj, cfg.model)
    topk_ids = np.asarray(jax.lax.top_k(logits, k)[1])
    sk, _ = generate(params, tj, lj, cfg.model, 1, temperature=1.5, top_k=k, seed=11)
    first = np.asarray(sk)[np.arange(2), lengths]
    for b in range(2):
        assert first[b] in topk_ids[b], (first[b], topk_ids[b])


def test_many_eos_early_exit():
    """ISSUE 5 satellite: rows that emit ``eos_id`` freeze — the EOS lands
    in the buffer, nothing after it is written, per-row lengths stop —
    and the stream prefix matches the unfrozen run exactly."""
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import init_params

    cfg = _mpt_cfg(alibi=False)
    params = init_params(cfg.model, seed=4)
    fn = make_cached_generate_fn(cfg.model, params)
    rng = np.random.default_rng(2)
    tokens = np.zeros((3, 20), np.int32)
    lengths = np.asarray([4, 6, 9], np.int32)
    for i, ln in enumerate(lengths):
        tokens[i, :ln] = rng.integers(1, cfg.model.vocab_size, ln)
    gen = 8

    full, _ = fn.many(jnp.asarray(tokens), jnp.asarray(lengths), gen)
    full = np.asarray(full)
    # pick an EOS every row emits (so the batch CAN fully finish early):
    # each row's first generated token works iff shared; else fall back to
    # row 0's and only row 0 freezes
    streams = [list(full[i, lengths[i]:lengths[i] + gen]) for i in range(3)]
    eos = int(streams[0][0])

    got, got_len = fn.many(jnp.asarray(tokens), jnp.asarray(lengths), gen,
                           eos_id=eos)
    got = np.asarray(got)
    for i in range(3):
        s = streams[i]
        cut = s.index(eos) + 1 if eos in s else gen
        np.testing.assert_array_equal(
            got[i, lengths[i]:lengths[i] + cut], s[:cut])
        # frozen tail: untouched buffer (zeros), not post-EOS tokens
        np.testing.assert_array_equal(got[i, lengths[i] + cut:], 0)
        assert int(got_len[i]) == int(lengths[i]) + cut


def test_many_eos_all_done_first_step():
    """Every row EOSes at its first token → produced lengths are +1 and the
    rest of the buffer stays untouched regardless of ``n``."""
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import init_params

    cfg = _mpt_cfg(alibi=False)
    params = init_params(cfg.model, seed=4)
    fn = make_cached_generate_fn(cfg.model, params)
    tokens = jnp.zeros((2, 64), jnp.int32).at[:, :3].set(5)
    lengths = jnp.asarray([3, 3], jnp.int32)
    probe, _ = fn.many(tokens, lengths, 1)
    eos = int(np.asarray(probe)[0, 3])  # both rows: same prompt, same token

    got, got_len = fn.many(tokens, lengths, 60, eos_id=eos)
    got = np.asarray(got)
    assert list(np.asarray(got_len)) == [4, 4]
    np.testing.assert_array_equal(got[:, 4:], 0)


def test_decode_jit_pair_shared_across_instances():
    """ISSUE 5 satellite: equal configs share ONE jitted prefill/step pair
    (no re-trace per gauntlet/eval construction); different configs don't."""
    from photon_tpu.models.decode import decode_jit_pair

    a = decode_jit_pair(_mpt_cfg(alibi=False).model)
    b = decode_jit_pair(_mpt_cfg(alibi=False).model)  # fresh but equal config
    assert a[0] is b[0] and a[1] is b[1]
    c = decode_jit_pair(_mpt_cfg(alibi=True).model)
    assert c[0] is not a[0]


def test_cached_generate_matches_full_forward_bf16():
    """The production compute dtype: bf16 end to end, cached == full."""
    from photon_tpu.eval.icl import make_generate_fn
    from photon_tpu.models.decode import make_cached_generate_fn
    from photon_tpu.models.mpt import MPTModel, init_params

    cfg = _mpt_cfg(alibi=True)
    cfg.model.compute_dtype = "bfloat16"
    cfg.validate()
    params = init_params(cfg.model, seed=6)
    model = MPTModel(cfg.model)
    tokens = np.zeros((2, 12), np.int32)
    tokens[0, :4] = [5, 9, 2, 7]
    tokens[1, :6] = [3, 3, 8, 1, 4, 2]
    lengths = np.asarray([4, 6], np.int32)

    oracle = make_generate_fn(lambda p, t: model.apply({"params": p}, t), params)
    t_o, c_o = jnp.asarray(tokens), jnp.asarray(lengths)
    for _ in range(5):
        t_o, c_o = oracle(t_o, c_o)
    cached = make_cached_generate_fn(cfg.model, params)
    t_c, _ = cached.many(jnp.asarray(tokens), jnp.asarray(lengths), 5)
    np.testing.assert_array_equal(np.asarray(t_o), np.asarray(t_c))
