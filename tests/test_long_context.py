"""Long-context training: seq 4096 through the FULL train step with ring
attention over the sequence axis.

The brief makes long context first-class (ring / context parallelism for
long sequences); the reference caps at seq 2048 with single-GPU flash
attention (SURVEY §5 "Long-context: absent"). The kernel-level ring tests
stop at seq 64 — this one trains at 2x the reference's maximum length on a
``sequence=4`` mesh and must reproduce the single-device loss trajectory,
proving the k/v-rotation (ppermute) path composes with grad accumulation,
chunked CE, and the optimizer at real length.
"""

import numpy as np
import pytest

from photon_tpu.config.schema import (
    Config,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
)
from photon_tpu.train.trainer import Trainer

SEQ = 4096

LONG = ModelConfig(
    d_model=32, n_layers=1, n_heads=2, max_seq_len=SEQ, vocab_size=128,
    attn_impl="xla", compute_dtype="float32",
)


def _cfg(mesh: MeshConfig, attn: str) -> Config:
    model = ModelConfig(**{**LONG.__dict__, "attn_impl": attn})
    return Config(
        model=model,
        mesh=mesh,
        optimizer=OptimizerConfig(name="adamw", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=100),
        train=TrainConfig(global_batch_size=2, device_microbatch_size=2),
    )


@pytest.mark.slow
def test_seq4096_ring_training_matches_single_device():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, LONG.vocab_size, (2, SEQ), dtype=np.int64)

    def run(mesh: MeshConfig, attn: str) -> list[float]:
        t = Trainer(_cfg(mesh, attn), init_seed=0)
        losses = []
        for _ in range(3):
            m = t.fit([tokens], duration_steps=1)
            losses.append(m["loss"])
        return losses

    ref = run(MeshConfig(), "xla")  # single device, full attention
    ring = run(MeshConfig(sequence=4), "ring")  # 4-way context parallel
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-5)
    # warmup lr is 0 at the first step (losses[0] == losses[1] by design);
    # by the third the repeated batch must be learned a little
    assert ref[2] < ref[0]
