"""Gauntlet YAML suite tests: parsing both reference formats, fewshot prompt
assembly, batched MC scoring across rows, category aggregation with
baseline subtraction + rescale, and the end-to-end demo corpus run."""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.tokenizer import ByteTokenizer
from photon_tpu.eval.gauntlet import GauntletConfig, TaskSuite, run_gauntlet_suite
from photon_tpu.eval.icl import ICLTask, evaluate_task, make_logprob_fn

VOCAB = 257
SEQ = 64
CONFIGS = pathlib.Path("photon_tpu/eval/configs")


def _apply(params, tokens):
    """Deterministic fake model: next byte = current + 1 (jit-traceable)."""
    nxt = (tokens + 1) % VOCAB
    return 20.0 * jax.nn.one_hot(nxt, VOCAB, dtype=jnp.float32) - 10.0


# -- YAML parsing -----------------------------------------------------------


def test_parse_reference_task_suite_format():
    suite = TaskSuite.from_yaml(CONFIGS / "tasks_demo.yaml")
    labels = {s.label for s in suite.specs}
    assert labels == {"arc_demo", "copa_demo", "lambada_demo", "gsm_demo"}
    arc = next(s for s in suite.specs if s.label == "arc_demo")
    assert arc.icl_task_type == "multiple_choice"
    assert arc.num_fewshot == (2,)
    assert arc.continuation_delimiter == "\nAnswer: "
    gsm = next(s for s in suite.specs if s.label == "gsm_demo")
    assert gsm.scoreable  # generation tasks score via batched greedy decode
    assert gsm.cot_delimiter == 'The answer is '


def test_parse_reference_gauntlet_format():
    g = GauntletConfig.from_yaml(CONFIGS / "gauntlet_demo.yaml")
    assert g.weighting == "EQUAL"
    assert g.subtract_random_baseline and g.rescale_accuracy
    assert set(g.categories) == {
        "world_knowledge", "commonsense_reasoning", "language_understanding",
        "symbolic_problem_solving",
    }
    assert g.averages["core_average"] == [
        "world_knowledge", "commonsense_reasoning", "language_understanding",
        "symbolic_problem_solving",
    ]
    assert g.labels_fewshot() == {
        "arc_demo": 2, "copa_demo": 0, "lambada_demo": 0, "gsm_demo": 0
    }


def test_suite_loads_all_four_task_types():
    suite = TaskSuite.from_yaml(CONFIGS / "tasks_demo.yaml")
    tasks, skipped = suite.load_tasks()
    assert {t.name for t in tasks} == {"arc_demo", "copa_demo", "lambada_demo", "gsm_demo"}
    assert skipped == []


def test_suite_type_mismatch_raises(tmp_path):
    (tmp_path / "t.jsonl").write_text(json.dumps({"context": "a", "continuation": "b"}))
    (tmp_path / "suite.yaml").write_text(
        "icl_tasks:\n  - label: t\n    dataset_uri: t.jsonl\n"
        "    icl_task_type: multiple_choice\n"
    )
    suite = TaskSuite.from_yaml(tmp_path / "suite.yaml")
    with pytest.raises(ValueError, match="look like"):
        suite.load_tasks()


# -- fewshot + batched MC ---------------------------------------------------


def test_fewshot_context_assembly():
    rows = [
        {"query": "q0", "choices": ["a", "b"], "gold": 0},
        {"query": "q1", "choices": ["a", "b"], "gold": 1},
        {"query": "q2", "choices": ["a", "b"], "gold": 0},
    ]
    task = ICLTask(
        "t", "multiple_choice", rows, num_fewshot=2,
        continuation_delimiter=": ", example_delimiter="\n",
    )
    ctx = task.build_context(1)  # scored row must be excluded from shots
    assert ctx == "q0: a\nq2: a\nq1: "


def test_batched_mc_matches_per_row_dispatch():
    """Scoring across row boundaries in full batches must give the same
    accuracy as the old one-batch-per-row dispatch (batch_size smaller than
    a row's choice count would previously have raised)."""
    tok = ByteTokenizer()
    rows = [
        {"query": "abcd", "choices": ["efgh", "zzzz", "qqqq"], "gold": 0},
        {"query": "mnop", "choices": ["xxxx", "qrst", "aaaa"], "gold": 1},
        {"query": "stuv", "choices": ["wxyz", "bbbb", "cccc"], "gold": 0},
    ]
    task = ICLTask("asc", "multiple_choice", rows)
    fn = make_logprob_fn(_apply, None, SEQ)
    # batch 2 < 3 choices: only possible with cross-row batching
    res = evaluate_task(task, tok, fn, SEQ, batch_size=2)
    assert res["accuracy"] == 1.0
    res8 = evaluate_task(task, tok, fn, SEQ, batch_size=8)
    assert res8["accuracy"] == 1.0


# -- aggregation ------------------------------------------------------------


def test_aggregate_subtract_and_rescale():
    from photon_tpu.eval.gauntlet import Benchmark

    g = GauntletConfig(
        categories={"cat_a": [Benchmark("b1", 0, 0.25)]},
    )
    out = g.aggregate({"b1": 0.625})
    # (0.625 - 0.25) / 0.75 = 0.5
    assert out["gauntlet/cat_a/b1"] == pytest.approx(0.5)
    assert out["gauntlet/category/cat_a"] == pytest.approx(0.5)
    assert out["gauntlet/average"] == pytest.approx(0.5)


def test_aggregate_named_averages_and_floor():
    from photon_tpu.eval.gauntlet import Benchmark

    g = GauntletConfig(
        categories={
            "good": [Benchmark("b1", 0, 0.5)],
            "bad": [Benchmark("b2", 0, 0.5)],
            "other": [Benchmark("b3", 0, 0.0)],
        },
        averages={"core": ["good", "bad"]},
    )
    out = g.aggregate({"b1": 1.0, "b2": 0.2, "b3": 0.4})
    assert out["gauntlet/good/b1"] == pytest.approx(1.0)
    assert out["gauntlet/bad/b2"] == 0.0  # below baseline: floored, not negative
    assert out["gauntlet/core"] == pytest.approx(0.5)
    assert out["gauntlet/average"] == pytest.approx((1.0 + 0.0 + 0.4) / 3)


# -- end to end -------------------------------------------------------------


def test_demo_corpus_end_to_end():
    tok = ByteTokenizer()
    out = run_gauntlet_suite(
        CONFIGS / "tasks_demo.yaml",
        CONFIGS / "gauntlet_demo.yaml",
        tok, _apply, params=None, seq_len=128, batch_size=8,
    )
    # all three scoreable benchmarks produced raw + adjusted scores
    for key in (
        "icl/arc_demo/accuracy",
        "icl/copa_demo/accuracy",
        "icl/lambada_demo/logprob_per_token",
        "gauntlet/category/world_knowledge",
        "gauntlet/core_average",
        "gauntlet/average",
    ):
        assert key in out, key
    assert "gauntlet/skipped_tasks" not in out  # all four types score now
    assert "icl/gsm_demo/accuracy" in out
    assert 0.0 <= out["icl/arc_demo/accuracy"] <= 1.0
