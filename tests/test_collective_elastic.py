"""Elastic collective rounds (ISSUE 8): stage deadlines, gang
reconfiguration, quorum + host-fallback degradation, chaos crash phases.

The failure unit is the *participant row* of the (clients, replica) mesh: a
client whose fit dies (chaos SIGKILL stand-in), a liveness live→suspect
edge mid-round, or a wedged exchange (missed stage deadline) must drop the
participant from THIS round's cohort and still complete the round —
reconfigured collective when quorum holds, host-plane ``aggregate_inplace``
fold when it doesn't — never abort the run. Reconfiguration is
round-scoped: the dead participant is readmitted at full strength the
round after it returns.

The e2es run under BOTH PR 6 dynamic detectors (lock-order recorder +
retrace sentinel): steady-state rounds with a stable cohort must stay
compile-free, while a legitimate reconfiguration compile is absorbed via
``absorb_compiles`` rather than billed as a retrace bug.

Deterministic under ``ChaosConfig(seed=1234)``; the fast half rides tier-1
via the ``chaos`` marker (``make chaos-collective`` runs the whole file).
"""

import pathlib
import time

import numpy as np
import pytest

from photon_tpu import chaos, telemetry
from photon_tpu.config.schema import Config, TelemetryConfig
from photon_tpu.federation.collective_round import (
    CollectiveFedRunner,
    StageDeadlineError,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_planes():
    yield
    chaos.uninstall()
    telemetry.uninstall()


def _cfg(tmp_path, strategy="fedavg", n_clients=4, quantization="off",
         device_opt=False, momenta=False, n_rounds=3) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 1
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 2
    cfg.train.device_microbatch_size = 2
    cfg.fl.n_total_clients = n_clients
    cfg.fl.n_clients_per_round = n_clients
    cfg.fl.n_rounds = n_rounds
    cfg.fl.local_steps = 1
    cfg.fl.eval_interval_rounds = 0
    cfg.fl.strategy_name = strategy
    cfg.fl.server_learning_rate = 1.0 if strategy == "fedavg" else 0.01
    cfg.fl.aggregate_momenta = momenta
    cfg.dataset.synthetic = True
    cfg.photon.checkpoint = False
    cfg.photon.comm_stack.collective = True
    cfg.photon.comm_stack.shm = False
    cfg.photon.comm_stack.collective_quantization = quantization
    cfg.photon.comm_stack.collective_q8_block = 64
    cfg.photon.comm_stack.collective_device_optimizer = device_opt
    cfg.photon.save_path = str(tmp_path / "run")
    cfg.run_uuid = "collective-elastic"
    cfg.validate()
    return cfg


def _oracle_params(params_before, lr, landed, cohort):
    """Survivors-only host oracle: ``aggregate_inplace`` over exactly the
    cohort's landed deltas + the FedAvg server step — the bit-exactness
    reference for degraded rounds."""
    from photon_tpu.strategy.aggregation import aggregate_inplace
    from photon_tpu.strategy.optimizers import FedAvgEff

    avg, n_total = aggregate_inplace(
        ([a.copy() for a in landed[cid][0]], landed[cid][1]) for cid in cohort
    )
    oracle = FedAvgEff(server_learning_rate=lr)
    oracle.initialize([p.copy() for p in params_before])
    oracle.apply_average(0, avg, n_total, len(cohort))
    return oracle.current_parameters


# ---------------------------------------------------------------------------
# stage-deadline unit tests (injectable clock — the PR 3 backoff pattern)
# ---------------------------------------------------------------------------


def _bare_runner(clock=time.monotonic, timeout=0.0):
    r = object.__new__(CollectiveFedRunner)
    r.clock = clock
    r.stage_timeout_s = timeout
    r._abandoned_workers = []
    return r


def test_stage_deadline_derived_from_injected_clock():
    r = _bare_runner(clock=lambda: 100.0, timeout=7.0)
    assert r._stage_deadline() == 107.0
    r.stage_timeout_s = 0.0
    assert r._stage_deadline() is None  # 0 = deadlines off


def test_expired_deadline_preempts_without_running_the_stage():
    now = [0.0]
    r = _bare_runner(clock=lambda: now[0], timeout=5.0)
    deadline = r._stage_deadline()  # 5.0
    now[0] = 6.0  # the round overran before this stage even dispatched
    ran = []
    with pytest.raises(StageDeadlineError) as ei:
        r._run_stage("exchange", lambda: ran.append(1), deadline)
    assert ei.value.stage == "exchange"
    assert not ran  # never dispatched — a wedged gang can't be re-entered


def test_wedged_stage_abandoned_at_the_deadline():
    r = _bare_runner(timeout=0.3)
    t0 = time.monotonic()
    with pytest.raises(StageDeadlineError):
        r._run_stage("exchange", lambda: time.sleep(5.0), r._stage_deadline())
    waited = time.monotonic() - t0
    assert waited < 2.0  # preempted at ~0.3s, not the wedge's 5s


def test_stage_errors_propagate_and_no_deadline_runs_inline():
    r = _bare_runner(timeout=0.5)
    with pytest.raises(ValueError, match="boom"):
        r._run_stage("update", lambda: (_ for _ in ()).throw(ValueError("boom")),
                     r._stage_deadline())
    r.stage_timeout_s = 0.0
    assert r._run_stage("stack", lambda: 42, r._stage_deadline()) == 42


def test_surviving_cohort_is_global_across_controllers():
    """Multi-controller semantics: ``landed`` only ever holds THIS
    process's cids, so peers' clients MUST stay in the cohort (they
    contribute their own psum rows) — a healthy multi-process round is a
    FULL cohort, never a 'reconfigured' one. Only a local fit failure or a
    shared-liveness exclusion removes a cid."""
    from photon_tpu.federation.membership import LivenessTracker

    r = object.__new__(CollectiveFedRunner)
    cfg = Config()
    cfg.fl.n_total_clients = 4
    r.cfg = cfg
    r._local_cids = frozenset([0, 1])  # controller 0 of 2
    r.liveness = LivenessTracker()

    row = ([np.zeros(2, np.float32)], 1)
    # both local fits landed → the cohort is the FULL four clients
    assert r._surviving_cohort({0: row, 1: row}) == (0, 1, 2, 3)
    # local cid 1 failed its fit → dropped; the peers' cids 2/3 stay
    assert r._surviving_cohort({0: row}) == (0, 2, 3)
    # the shared liveness plane rules out a PEER's client too
    for _ in range(2):
        r.liveness.observe_miss("client3")
    assert r._surviving_cohort({0: row, 1: row}) == (0, 1, 2)
    # single-controller (the tested-everywhere shape): landed covers all
    r._local_cids = frozenset([0, 1, 2, 3])
    assert r._surviving_cohort({0: row, 2: row}) == (0, 2)


# ---------------------------------------------------------------------------
# gang reconfiguration: client dies → survivors round → readmission, under
# both dynamic detectors (fused device plane + retrace absorb)
# ---------------------------------------------------------------------------


class SimKill(BaseException):
    """In-process stand-in for os._exit(137): a BaseException the elastic
    ladder must NOT absorb — the participant is gone, not retryable."""


def test_fit_crash_reconfigures_then_readmits_full_strength(tmp_path):
    """A client SIGKILLed in its round-2 fit (chaos mid-fit, one-shot via
    crash marker) drops from the cohort; the round completes over the
    survivors with the fused plane reseeded; round 3 runs the FULL cohort
    again on the cached program (round-scoped reconfiguration — the
    readmitted client never rejoins a torn gang). Compile-free from round 2
    except the absorbed reconfiguration compiles."""
    from photon_tpu.analysis import runtime as lint_rt

    cfg = _cfg(tmp_path, strategy="fedadam", n_clients=3, device_opt=True)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 2
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.validate()

    def _client_crash(code):
        raise RuntimeError(f"simulated SIGKILL ({code})")

    recorder = lint_rt.install_lock_order()
    sentinel = lint_rt.install_retrace_sentinel()
    try:
        chaos.install(cfg.photon.chaos, scope="collective0",
                      crash_fn=_client_crash)
        runner = CollectiveFedRunner(cfg, [0, 1, 2])
        assert runner.device_plane is not None
        sentinel.mark_steady_after(1)  # round 1 = warmup compiles
        with pytest.warns(UserWarning, match="dropped from the round's cohort"):
            m1 = runner.run_round(1)  # marker not armed for round 1
            m2 = runner.run_round(2)
        m3 = runner.run_round(3)
        sentinel.check("collective/elastic-e2e")
        recorder.check()
    finally:
        lint_rt.uninstall_retrace_sentinel()
        lint_rt.uninstall_lock_order()

    # round 1 + 3: full cohort, clean; round 2: one straggler, reconfigured
    assert m1["server/collective_stragglers"] == 0.0
    assert m1["server/collective_degraded_rounds"] == 0.0
    assert m2["server/collective_stragglers"] == 1.0
    assert m2["server/collective_degraded_rounds"] == 0.0
    assert m2["server/n_clients"] == 2.0
    assert m3["server/collective_stragglers"] == 0.0
    assert m3["server/n_clients"] == 3.0  # readmitted, full strength
    assert runner.aggregation_paths == {
        1: "collective", 2: "collective_reconfigured", 3: "collective",
    }
    # the survivors-cohort program compile was absorbed, not billed
    assert any(lbl == "collective/reconfig" for lbl, _ in sentinel.absorbed)
    # adaptive bias correction stayed continuous across the off-plane round
    assert runner.device_plane.t == 3
    for p in runner.strategy.current_parameters:
        assert np.all(np.isfinite(p))
    # liveness: the crashed client went suspect, then back live on rejoin
    h = runner.liveness.nodes["client0"]
    assert h.state == "live" and h.misses == 0
    # per-round history series exist for every new KPI
    for name in ("server/collective_stragglers",
                 "server/collective_degraded_rounds",
                 "server/collective_reconfig_time"):
        assert len(runner.history.series(name)) == 3, name


# ---------------------------------------------------------------------------
# the acceptance e2e: SIGKILL one client mid-round + wedged exchange → the
# round completes within its stage deadlines via the host fold over the
# survivors (bit-exact with the survivors-only oracle), dead client back at
# round N+1, fault-free rounds report zero stragglers / zero degraded
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quantization", ["off", "q8"])
def test_sigkill_mid_collective_degrades_bitexact_and_readmits(
    tmp_path, quantization, monkeypatch
):
    import photon_tpu.federation.collective_round as cr

    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=4,
               quantization=quantization)
    cfg.photon.comm_stack.collective_retry_budget = 0  # deadline → degrade
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 2
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.validate()

    events_path = tmp_path / "events.jsonl"
    telemetry.install(TelemetryConfig(enabled=True), scope="server",
                      events_path=str(events_path))

    def _client_crash(code):
        raise RuntimeError(f"simulated SIGKILL ({code})")

    inj = chaos.install(cfg.photon.chaos, scope="collective0",
                        crash_fn=_client_crash)
    runner = CollectiveFedRunner(cfg, [0, 1, 2, 3])

    # after the client death the torn gang's exchange WEDGES (the real
    # multi-controller failure shape): round 2's collective never returns,
    # and only the stage deadline can preempt it
    real_fold = cr.hierarchical_weighted_average
    state = {"wedge_round": 2, "round": 0}

    def wedging_fold(*args, **kwargs):
        if state["round"] == state["wedge_round"]:
            time.sleep(5.0)  # far past the stage deadline
        return real_fold(*args, **kwargs)

    monkeypatch.setattr(cr, "hierarchical_weighted_average", wedging_fold)

    state["round"] = 1
    m1 = runner.run_round(1)
    runner.stage_timeout_s = 0.5  # arm deadlines AFTER warmup compiles

    params_before = [p.copy() for p in runner.strategy.current_parameters]
    landed_spy = {}
    real_fallback = CollectiveFedRunner._host_fallback

    def spy_fallback(self, server_round, cohort, landed):
        landed_spy["cohort"] = cohort
        landed_spy["landed"] = {
            cid: ([a.copy() for a in arrs], n) for cid, (arrs, n) in landed.items()
        }
        return real_fallback(self, server_round, cohort, landed)

    monkeypatch.setattr(CollectiveFedRunner, "_host_fallback", spy_fallback)

    state["round"] = 2
    t0 = time.monotonic()
    with pytest.warns(UserWarning, match="degrading to the host-plane fold"):
        m2 = runner.run_round(2)
    round2_wall = time.monotonic() - t0
    params_after_degraded = [
        p.copy() for p in runner.strategy.current_parameters
    ]
    state["round"] = 3
    m3 = runner.run_round(3)

    # the dead client + wedge did not stall the round to the wedge's 5s:
    # the stage deadline (0.5s) preempted it
    assert round2_wall < 4.0
    assert inj.counts["crash"] == 1  # exactly one SIGKILL (marker one-shot)

    # round 2: one straggler, degraded to the host fold over the survivors
    assert m2["server/collective_stragglers"] == 1.0
    assert m2["server/collective_degraded_rounds"] == 1.0
    assert m2["server/collective_reconfig_time"] > 0.0
    assert m2["server/n_clients"] == 3.0
    assert m2["server/collective_wire_bytes"] == 0.0  # nothing crossed DCN
    assert runner.aggregation_paths[2] == "host_fallback"
    assert runner.degraded_rounds_total == 1

    # the degraded round's params BIT-EXACT with the survivors-only host
    # oracle — at `off` AND at `q8` (the degradation floor is the host
    # plane; it never quantizes, whatever the round's configured policy)
    cohort = landed_spy["cohort"]
    assert len(cohort) == 3 and 0 not in cohort  # cid 0 crashed first
    oracle = _oracle_params(params_before, 1.0, landed_spy["landed"], cohort)
    for got, want in zip(params_after_degraded, oracle):
        np.testing.assert_array_equal(got, want)

    # fault-free rounds (1 and 3) report zero stragglers, zero degraded;
    # round 3 has the dead client back at full strength
    for m in (m1, m3):
        assert m["server/collective_stragglers"] == 0.0
        assert m["server/collective_degraded_rounds"] == 0.0
    assert m3["server/n_clients"] == 4.0
    assert runner.aggregation_paths[3] == "collective"

    # the checkpointed control state records each round's aggregation path,
    # and a resumed runner restores it
    control = runner.control_state_for_checkpoint()
    assert control["aggregation_paths"] == {
        1: "collective", 2: "host_fallback", 3: "collective",
    }
    resumed = CollectiveFedRunner(
        _cfg(tmp_path / "resumed", strategy="fedavg", n_clients=4,
             quantization=quantization), [0, 1, 2, 3],
    )
    resumed.load_server_state(
        runner.strategy.current_parameters,
        runner.state_for_checkpoint(), control,
    )
    assert resumed.aggregation_paths[2] == "host_fallback"
    assert resumed.server_steps_cumulative == runner.server_steps_cumulative

    # structured events with the registry vocabulary landed in the JSONL
    telemetry.uninstall()
    kinds = [e["kind"] for e in telemetry.read_events_jsonl(str(events_path))]
    assert "collective/straggler" in kinds
    assert "collective/reconfig" in kinds
    assert "collective/degraded" in kinds
    assert any(k.startswith("chaos/") for k in kinds)


# ---------------------------------------------------------------------------
# liveness edges + partial-participation parity (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_liveness_edge_mid_round_excludes_client_with_parity(tmp_path, monkeypatch):
    """A live→suspect edge observed mid-round (after the fits, before the
    exchange — e.g. a shared control plane's ping sweep) excludes the
    client even though its delta landed; the survivors-only collective
    round matches the host oracle fed the same subset, and the client is
    back the next round once it answers again."""
    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=4)
    runner = CollectiveFedRunner(cfg, [0, 1, 2, 3])
    runner.run_round(1)

    params_before = [p.copy() for p in runner.strategy.current_parameters]
    landed_spy = {}
    real_agg = CollectiveFedRunner._aggregate_elastic
    fired = []

    def edge_then_agg(self, server_round, landed):
        if not fired:
            fired.append(1)
            self.liveness.observe_miss(self._client_node_id(2))
            landed_spy.update({
                cid: ([a.copy() for a in arrs], n)
                for cid, (arrs, n) in landed.items()
            })
        return real_agg(self, server_round, landed)

    monkeypatch.setattr(CollectiveFedRunner, "_aggregate_elastic", edge_then_agg)

    m2 = runner.run_round(2)
    assert m2["server/collective_stragglers"] == 1.0
    assert m2["server/collective_degraded_rounds"] == 0.0
    assert m2["server/n_clients"] == 3.0
    assert runner.aggregation_paths[2] == "collective_reconfigured"

    # parity: the reconfigured round == the survivors-only oracle (the
    # collective's fp32 psum vs the oracle's fp64 streaming fold — fp32
    # reduction-order tolerance, same pin as the full-cohort parity tests)
    oracle = _oracle_params(params_before, 1.0, landed_spy, (0, 1, 3))
    for got, want in zip(runner.strategy.current_parameters, oracle):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # the suspect client answered round 3's fit → live again, full cohort
    m3 = runner.run_round(3)
    assert m3["server/collective_stragglers"] == 0.0
    assert m3["server/n_clients"] == 4.0
    assert runner.liveness.nodes["client2"].state == "live"


def test_survivors_only_fold_parity_off_and_q8_bound():
    """The satellite's numeric pin, at the fold level: a survivors-subset
    hierarchical average (the exact program a reconfigured round runs)
    matches ``aggregate_inplace`` on the same subset at ``off``, and stays
    within the documented per-element blockwise bound at ``q8``."""
    import jax.numpy as jnp

    from photon_tpu.parallel.collective_agg import (
        hierarchical_weighted_average,
        make_hierarchical_mesh,
        stack_for_clients,
    )
    from photon_tpu.strategy.aggregation import aggregate_inplace
    from tests.test_collective_agg import _client_params, _expected_q8_bound

    block = 16
    clients = [_client_params(90 + i) for i in range(4)]
    counts = np.asarray([5, 11, 2, 31], np.int32)
    survivors = [0, 2, 3]  # client 1 died this round
    surv_clients = [clients[i] for i in survivors]
    surv_counts = counts[survivors]

    mesh = make_hierarchical_mesh(len(survivors), 1)
    stacked = stack_for_clients(surv_clients, mesh)
    off = hierarchical_weighted_average(
        stacked, jnp.asarray(surv_counts), mesh
    )
    host_avg, host_total = aggregate_inplace(
        ([c["w"], c["b"]], int(n)) for c, n in zip(surv_clients, surv_counts)
    )
    assert host_total == int(surv_counts.sum())  # weights renormalized
    np.testing.assert_allclose(np.asarray(off["w"]), host_avg[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(off["b"]), host_avg[1], rtol=1e-5, atol=1e-6)

    q8 = hierarchical_weighted_average(
        stacked, jnp.asarray(surv_counts), mesh, quantization="q8", block=block
    )
    for key in ("w", "b"):
        bound = _expected_q8_bound(surv_clients, surv_counts, key, mesh, block)
        err = np.abs(np.asarray(q8[key], np.float64) - np.asarray(off[key], np.float64))
        assert np.all(err <= bound + 1e-7), key


# ---------------------------------------------------------------------------
# chaos crash phases inside the collective: deterministic, one-shot, and a
# respawned controller resumes the NEXT round (never rejoins the torn gang)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("phase", ["pre-exchange", "mid-exchange", "pre-update"])
def test_collective_crash_phase_kills_controller_then_respawn_resumes(
    tmp_path, phase
):
    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=2)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = phase
    cfg.photon.chaos.crash_round = 2
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.validate()

    def _exit(code):
        raise SimKill(code)

    inj = chaos.install(cfg.photon.chaos, scope="collective0", crash_fn=_exit)
    runner = CollectiveFedRunner(cfg, [0, 1])
    runner.run_round(1)
    params_r1 = [p.copy() for p in runner.strategy.current_parameters]
    state_r1 = runner.state_for_checkpoint()
    control_r1 = runner.control_state_for_checkpoint()

    # SIGKILL-equivalent inside the collective: a BaseException the elastic
    # ladder must NOT swallow — the controller process is gone
    with pytest.raises(SimKill):
        runner.run_round(2)
    assert inj.counts["crash"] == 1
    assert pathlib.Path(cfg.photon.chaos.crash_marker).exists()

    # the respawned controller (same config; the marker disarms the crash)
    # re-seeds from the last checkpoint and runs round 2 from scratch — it
    # never tries to re-enter the torn round's half-finished collective
    respawn = CollectiveFedRunner(cfg, [0, 1])
    respawn.load_server_state(params_r1, state_r1, control_r1)
    m2 = respawn.run_round(2)
    assert m2["server/collective_stragglers"] == 0.0
    assert inj.counts["crash"] == 1  # marker held: exactly once
    assert respawn.aggregation_paths[2] == "collective"


# ---------------------------------------------------------------------------
# quorum + zero-landed floors
# ---------------------------------------------------------------------------


def test_below_quorum_degrades_directly_bitexact(tmp_path, monkeypatch):
    """Two of four clients dead → 0.5 < quorum 0.75: no collective attempt,
    straight to the host fold, bit-exact with the survivors-only oracle."""
    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=4)
    cfg.photon.comm_stack.collective_quorum = 0.75
    cfg.validate()
    runner = CollectiveFedRunner(cfg, [0, 1, 2, 3])
    runner.run_round(1)

    real_fit = runner.runtime.fit

    def failing_fit(ins, cid):
        if ins.server_round == 2 and cid in (1, 2):
            from photon_tpu.federation.messages import FitRes

            return FitRes(server_round=ins.server_round, cid=cid, params=None,
                          error="simulated node loss")
        return real_fit(ins, cid)

    monkeypatch.setattr(runner.runtime, "fit", failing_fit)

    params_before = [p.copy() for p in runner.strategy.current_parameters]
    landed_spy = {}
    real_fallback = CollectiveFedRunner._host_fallback

    def spy_fallback(self, server_round, cohort, landed):
        landed_spy["cohort"] = cohort
        landed_spy["landed"] = {
            cid: ([a.copy() for a in arrs], n) for cid, (arrs, n) in landed.items()
        }
        return real_fallback(self, server_round, cohort, landed)

    monkeypatch.setattr(CollectiveFedRunner, "_host_fallback", spy_fallback)

    with pytest.warns(UserWarning, match="below quorum"):
        m2 = runner.run_round(2)
    assert m2["server/collective_stragglers"] == 2.0
    assert m2["server/collective_degraded_rounds"] == 1.0
    assert m2["server/collective_reconfig_time"] == 0.0  # no failed attempts
    assert landed_spy["cohort"] == (0, 3)
    oracle = _oracle_params(params_before, 1.0, landed_spy["landed"], (0, 3))
    for got, want in zip(runner.strategy.current_parameters, oracle):
        np.testing.assert_array_equal(got, want)


def test_zero_landed_round_recorded_failed_never_aborts(tmp_path, monkeypatch):
    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=2)
    runner = CollectiveFedRunner(cfg, [0, 1])
    runner.run_round(1)
    params_before = [p.copy() for p in runner.strategy.current_parameters]
    steps_before = runner.server_steps_cumulative

    real_fit = runner.runtime.fit

    def all_fail(ins, cid):
        if ins.server_round == 2:
            from photon_tpu.federation.messages import FitRes

            return FitRes(server_round=ins.server_round, cid=cid, params=None,
                          error="simulated node loss")
        return real_fit(ins, cid)

    monkeypatch.setattr(runner.runtime, "fit", all_fail)
    with pytest.warns(UserWarning, match="no client deltas landed"):
        m2 = runner.run_round(2)
    assert m2["server/round_failed"] == 1.0
    assert m2["server/collective_stragglers"] == 2.0
    assert runner.aggregation_paths[2] == "failed"
    # parameters and the cumulative step counter are untouched
    for got, want in zip(runner.strategy.current_parameters, params_before):
        np.testing.assert_array_equal(got, want)
    assert runner.server_steps_cumulative == steps_before
    # ... and the run continues
    m3 = runner.run_round(3)
    assert m3["server/collective_stragglers"] == 0.0
    assert m3["server/n_clients"] == 2.0


# ---------------------------------------------------------------------------
# eval elasticity: a failed eval scores zero weight; a wedged eval exchange
# falls back to the local weighted mean (never aborts the surviving run)
# ---------------------------------------------------------------------------


def test_eval_survives_client_failure_and_wedged_exchange(tmp_path, monkeypatch):
    import photon_tpu.federation.collective_round as cr
    from photon_tpu.federation.messages import EvaluateRes

    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=2)
    runner = CollectiveFedRunner(cfg, [0, 1])
    runner.run_round(1)
    e0 = runner.evaluate_round(1)  # clean baseline
    assert e0["server/eval_samples"] > 0

    # one client's eval fails: zero-weight row, the weighted mean is
    # exactly the surviving client's loss
    real_eval = runner.runtime.evaluate

    def failing_eval(ins, cid):
        if cid == 0:
            return EvaluateRes(server_round=ins.server_round, cid=cid,
                               error="simulated eval node loss")
        return real_eval(ins, cid)

    monkeypatch.setattr(runner.runtime, "evaluate", failing_eval)
    with pytest.warns(UserWarning, match="scored with zero weight"):
        e1 = runner.evaluate_round(1)
    assert 0 < e1["server/eval_samples"] < e0["server/eval_samples"]
    assert np.isfinite(e1["server/eval_loss"])

    # the eval exchange wedges: the stage deadline preempts it and the
    # metric degrades to the local weighted mean instead of wedging/raising
    runner.stage_timeout_s = 0.4
    real_fold = cr.hierarchical_weighted_average

    def wedging_fold(*args, **kwargs):
        time.sleep(3.0)
        return real_fold(*args, **kwargs)

    monkeypatch.setattr(cr, "hierarchical_weighted_average", wedging_fold)
    with pytest.warns(UserWarning, match="local weighted mean"):
        e2 = runner.evaluate_round(1)
    assert e2["server/eval_samples"] == e1["server/eval_samples"]
    assert e2["server/eval_loss"] == pytest.approx(e1["server/eval_loss"], rel=1e-5)


# ---------------------------------------------------------------------------
# retry-budget ladder: transient wedge → reconfig retry → clean completion
# ---------------------------------------------------------------------------


def test_update_stage_wedge_never_double_applies(tmp_path, monkeypatch):
    """A fused attempt can fail AFTER its device commit (exchange lands,
    the update-stage fetch misses its deadline). The retry must re-apply
    the round ONCE — the plane rolls back to the attempt snapshot — and an
    abandoned fetch worker must never mutate the strategy later. Pinned
    against an identical unwedged runner: same params, t advanced once."""
    cfg = _cfg(tmp_path, strategy="fedadam", n_clients=2, device_opt=True)
    ref = CollectiveFedRunner(cfg, [0, 1])
    ref.run_round(1)
    ref.run_round(2)

    runner = CollectiveFedRunner(cfg, [0, 1])
    runner.run_round(1)
    assert runner.device_plane.t == 1

    real_fetch = runner.device_plane.params_host
    wedges = []

    def wedge_once():
        if not wedges:
            wedges.append(1)
            time.sleep(3.0)  # past the stage deadline: fetch looks dead
        return real_fetch()

    monkeypatch.setattr(runner.device_plane, "params_host", wedge_once)
    runner.stage_timeout_s = 0.4  # arm AFTER warmup compiles
    with pytest.warns(UserWarning, match="reconfiguring"):
        m2 = runner.run_round(2)

    # the committed first attempt was rolled back before the retry:
    # the optimizer stepped exactly once, params match the clean run
    assert runner.device_plane.t == 2
    assert m2["server/collective_degraded_rounds"] == 0.0
    assert m2["server/collective_stragglers"] == 0.0
    assert runner.aggregation_paths[2] == "collective"
    for got, want in zip(runner.strategy.current_parameters,
                         ref.strategy.current_parameters):
        np.testing.assert_array_equal(got, want)
    # let the abandoned fetch worker finish: it must not have touched the
    # strategy (the caller thread owns the host-mirror mutation)
    time.sleep(3.2)
    for got, want in zip(runner.strategy.current_parameters,
                         ref.strategy.current_parameters):
        np.testing.assert_array_equal(got, want)


def test_transient_wedge_retries_within_budget(tmp_path, monkeypatch):
    import photon_tpu.federation.collective_round as cr

    cfg = _cfg(tmp_path, strategy="fedavg", n_clients=2)
    assert cfg.photon.comm_stack.collective_retry_budget == 1
    runner = CollectiveFedRunner(cfg, [0, 1])
    runner.run_round(1)
    runner.stage_timeout_s = 0.4  # arm AFTER warmup compiles

    real_fold = cr.hierarchical_weighted_average
    wedges = []

    def wedge_once(*args, **kwargs):
        if not wedges:
            wedges.append(1)
            time.sleep(3.0)  # transient stall, first attempt only
        return real_fold(*args, **kwargs)

    monkeypatch.setattr(cr, "hierarchical_weighted_average", wedge_once)
    with pytest.warns(UserWarning, match="reconfiguring"):
        m2 = runner.run_round(2)
    # second attempt landed on the full cohort: collective, not degraded
    assert m2["server/collective_degraded_rounds"] == 0.0
    assert m2["server/collective_stragglers"] == 0.0
    assert m2["server/collective_reconfig_time"] > 0.0
    assert runner.aggregation_paths[2] == "collective"
    assert runner.reconfigs_total == 1
