"""The post-parity bench evidence stages (CONVERGENCE_TPU.json /
PERF_1B_MEASURED.json writers) run end to end with tiny monkeypatched
configs on CPU — the on-chip run only changes the dims and the platform
stamp, so the artifact plumbing (incremental atomic writes, deadline
skips, loss curves, predicted-vs-measured fields) is what these cover."""

import json

import numpy as np
import pytest


@pytest.fixture
def bench(monkeypatch, tmp_path):
    import bench as bench_mod

    # keep artifacts out of the repo root during tests
    monkeypatch.setattr(bench_mod, "HERE", tmp_path)
    return bench_mod


class _FakeDev:
    """Stats grow per call so the probe's pre/post live-bytes delta is
    non-trivial: first call (pre-probe) 123 MiB, second (post-step) 444."""

    platform = "cpu"
    device_kind = "cpu"

    def __init__(self):
        self._calls = 0

    def memory_stats(self):
        self._calls += 1
        live = (123 if self._calls == 1 else 444) * 2**20
        return {"bytes_in_use": live, "peak_bytes_in_use": 456 * 2**20}


from photon_tpu.config.schema import Config as _RealConfig


def _tiny_cfg():
    cfg = _RealConfig()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.remat = False
    return cfg


def test_convergence_slice_writes_curves(bench, monkeypatch, tmp_path):
    import photon_tpu.config.schema as schema

    monkeypatch.setattr(schema, "Config", _tiny_cfg)
    # 2-row batches over a synthetic byte stream; 4 steps -> one eval point
    monkeypatch.setattr(
        bench, "_corpus_tokens",
        lambda: np.random.default_rng(0).integers(0, 64, 3000).astype(np.uint8),
    )
    monkeypatch.setenv("PHOTON_BENCH_CONV_GBS", "2")
    monkeypatch.setenv("PHOTON_BENCH_CONV_STEPS", "4")
    monkeypatch.setenv("PHOTON_BENCH_MICROBATCH", "2")
    monkeypatch.delenv("PHOTON_BENCH_CHILD_DEADLINE", raising=False)
    monkeypatch.delenv("PHOTON_BENCH_FLASH_BLOCK", raising=False)

    bench.tpu_convergence_slice(_FakeDev())

    out = json.loads((tmp_path / "CONVERGENCE_TPU.json").read_text())
    assert out["complete"], out.get("error")
    assert out["steps"] == 4 and out["global_batch"] == 2
    assert len(out["train_loss"]) == 1 and len(out["val_loss"]) == 1
    assert np.isfinite(out["val_loss"][0][1])
    assert out["tokens_per_sec"] > 0
    assert "val_loss_drop" in out


def test_convergence_slice_deadline_skip(bench, monkeypatch, tmp_path):
    import time

    monkeypatch.setenv("PHOTON_BENCH_CHILD_DEADLINE", str(time.time() + 10))
    bench.tpu_convergence_slice(_FakeDev())
    assert not (tmp_path / "CONVERGENCE_TPU.json").exists()


def _tiny_byte_cfg():
    """Byte-tokenizer-compatible tiny model (vocab must cover ids < 256)."""
    cfg = _tiny_cfg()
    cfg.model.vocab_size = 320
    cfg.model.max_seq_len = 64
    return cfg


def test_convergence_slice_returns_params_and_gauntlet_scores(
    bench, monkeypatch, tmp_path
):
    """The conv slice hands its trained params to the gauntlet stage, which
    writes GAUNTLET_TPU.json with per-task scores through the real scorers
    (byte tokenizer, cached decoder for the generation task)."""
    import photon_tpu.config.schema as schema

    monkeypatch.setattr(schema, "Config", _tiny_byte_cfg)
    monkeypatch.setattr(
        bench, "_corpus_tokens",
        lambda: np.random.default_rng(0).integers(0, 250, 4000).astype(np.uint8),
    )
    monkeypatch.setattr(bench, "_GAUNTLET_SLICE_TASKS", [
        "symbolic_problem_solving/svamp.jsonl",
        "commonsense_reasoning/copa_demo.jsonl",
    ])
    # the stage resolves task files relative to HERE, which the fixture
    # moved to tmp_path — point it back at the repo's local_data
    import pathlib

    (tmp_path / "photon_tpu" / "eval").mkdir(parents=True)
    (tmp_path / "photon_tpu" / "eval" / "local_data").symlink_to(
        pathlib.Path(__file__).parent.parent / "photon_tpu" / "eval" / "local_data"
    )
    monkeypatch.setenv("PHOTON_BENCH_CONV_GBS", "2")
    monkeypatch.setenv("PHOTON_BENCH_CONV_STEPS", "2")
    monkeypatch.setenv("PHOTON_BENCH_MICROBATCH", "2")
    monkeypatch.delenv("PHOTON_BENCH_CHILD_DEADLINE", raising=False)
    monkeypatch.delenv("PHOTON_BENCH_FLASH_BLOCK", raising=False)

    params = bench.tpu_convergence_slice(_FakeDev())
    assert params is not None and "wte" in params

    bench.gauntlet_on_slice(params, _FakeDev())
    out = json.loads((tmp_path / "GAUNTLET_TPU.json").read_text())
    assert out["complete"], out.get("error")
    assert set(out["tasks"]) == {"svamp", "copa_demo"}
    assert "icl/average" in out["scores"]


def test_conv_slice_persists_params_for_cross_process_gauntlet(
    bench, monkeypatch, tmp_path
):
    """In stage-orchestration mode (--stage conv) the trained params are
    serialized atomically for the gauntlet stage's separate process, and
    _load_slice_params round-trips them; without the env flag (inline
    --run mode, in-memory handoff) nothing is written."""
    import photon_tpu.config.schema as schema

    monkeypatch.setattr(schema, "Config", _tiny_byte_cfg)
    monkeypatch.setattr(
        bench, "_corpus_tokens",
        lambda: np.random.default_rng(0).integers(0, 250, 4000).astype(np.uint8),
    )
    params_path = tmp_path / ".conv_slice_params.msgpack"
    monkeypatch.setattr(bench, "SLICE_PARAMS_PATH", params_path)
    monkeypatch.setenv("PHOTON_BENCH_CONV_GBS", "2")
    monkeypatch.setenv("PHOTON_BENCH_CONV_STEPS", "2")
    monkeypatch.setenv("PHOTON_BENCH_MICROBATCH", "2")
    monkeypatch.delenv("PHOTON_BENCH_CHILD_DEADLINE", raising=False)
    monkeypatch.delenv("PHOTON_BENCH_FLASH_BLOCK", raising=False)
    monkeypatch.delenv("PHOTON_BENCH_SAVE_SLICE_PARAMS", raising=False)

    params = bench.tpu_convergence_slice(_FakeDev())
    assert params is not None
    assert not params_path.exists()  # inline mode: in-memory handoff only

    monkeypatch.setenv("PHOTON_BENCH_SAVE_SLICE_PARAMS", "1")
    bench.tpu_convergence_slice(_FakeDev())
    assert params_path.exists()
    restored = bench._load_slice_params()
    np.testing.assert_array_equal(
        np.asarray(restored["wte"]["embedding"]),
        np.asarray(params["wte"]["embedding"]),
    )


def test_one_b_probe_predicted_vs_measured(bench, monkeypatch, tmp_path):
    import photon_tpu.config as config_mod

    monkeypatch.setattr(config_mod, "load_preset", lambda name: _tiny_cfg())
    monkeypatch.setenv("PHOTON_BENCH_1B_LAYERS", "2")
    monkeypatch.delenv("PHOTON_BENCH_CHILD_DEADLINE", raising=False)

    bench.one_b_memory_probe(_FakeDev())

    out = json.loads((tmp_path / "PERF_1B_MEASURED.json").read_text())
    assert out["complete"], out.get("error")
    assert out["n_params"] > 0
    assert np.isfinite(out["final_loss"])
    # the fake dev reports stats, so the measured fields must be present:
    # live = post-step minus pre-probe (444 - 123 MiB), peak = lifetime
    assert out["pre_probe_live_gib"] == pytest.approx(round(123 / 1024, 2))
    assert out["measured_live_gib"] == pytest.approx(round((444 - 123) / 1024, 2))
    assert out["process_lifetime_peak_gib"] == pytest.approx(round(456 / 1024, 2))
    # predicted may be None-gated on backends without memory_analysis, but
    # CPU provides it — require the args-vs-live ratio when both sides exist
    if "predicted_args_gib" in out:
        assert "predicted_over_measured" in out
