"""Mixture-of-Experts (``ops/moe.py``): GShard-style dense dispatch with
static capacity, sharded over the ``expert`` mesh axis. No reference
analog (the reference's models are dense) — correctness anchors are the
routing invariants, a dense-equivalence construction, and single-device
vs expert-parallel bitwise-level agreement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding

from photon_tpu.config.schema import Config, MeshConfig
from photon_tpu.models.mpt import MPTModel, init_params
from photon_tpu.ops.moe import expert_capacity, moe_mlp, route
from photon_tpu.parallel.mesh import make_mesh
from photon_tpu.parallel.sharding import batch_spec, param_specs, state_shardings
from photon_tpu.train.train_step import init_train_state, make_loss_fn


def test_route_invariants():
    n, e, k, cap = 24, 4, 2, 8
    probs = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(0), (n, e)), -1)
    dispatch, combine, aux = route(probs, k, cap)
    assert dispatch.shape == (n, e, cap)
    # every expert buffer slot holds at most one token
    assert float(dispatch.sum((0,)).max()) <= 1.0 + 1e-6
    # each token occupies at most k slots in total
    assert float(dispatch.sum((1, 2)).max()) <= k + 1e-6
    # per-expert load never exceeds capacity
    assert float(dispatch.sum((0, 2)).max()) <= cap + 1e-6
    # combine weights per token sum to 1 for tokens that kept >= 1 expert
    tok_w = combine.sum((1, 2))
    kept = dispatch.sum((1, 2)) > 0
    np.testing.assert_allclose(np.asarray(tok_w)[np.asarray(kept)], 1.0, atol=1e-5)
    assert float(aux) > 0.0  # E * sum(f*p) >= 1 at any routing


def test_route_capacity_overflow_drops_lowest_priority():
    # all tokens prefer expert 0 with capacity 2: only 2 slots filled
    n, e = 6, 2
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]]), (n, 1))
    dispatch, combine, _ = route(probs, 1, 2)
    assert float(dispatch[:, 0].sum()) == 2.0  # capacity-bound
    assert float(dispatch[:, 1].sum()) == 0.0  # nobody chose expert 1
    # dropped tokens carry zero combine weight (residual passthrough)
    assert float(combine.sum()) == pytest.approx(2.0, abs=1e-5)


def test_moe_mlp_single_expert_equals_dense():
    """E=1, top-1, ample capacity: routing is the identity and the MoE MLP
    must equal the plain dense FFN with the same weights."""
    b, s, d, h = 2, 8, 16, 32
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (b, s, d), jnp.float32)
    w_up = jax.random.normal(jax.random.PRNGKey(1), (1, d, h)) * 0.1
    w_down = jax.random.normal(jax.random.PRNGKey(2), (1, h, d)) * 0.1
    router = jnp.zeros((d, 1))
    out, aux = moe_mlp(x, router, w_up, w_down, top_k=1, capacity_factor=1.0)
    dense = jax.nn.gelu(x @ w_up[0]) @ w_down[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5)
    assert float(aux) == pytest.approx(1.0, abs=1e-5)  # E·f·p = 1·1·1


def _moe_cfg(mesh: MeshConfig) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.mlp = "moe"
    cfg.model.moe_num_experts = 4
    cfg.model.moe_top_k = 2
    cfg.mesh = mesh
    cfg.train.global_batch_size = 8
    cfg.train.device_microbatch_size = 4
    return cfg.validate()


@pytest.mark.parametrize(
    "mesh,act", [(MeshConfig(expert=4), "gelu"),
                 (MeshConfig(data=2, expert=2), "swiglu"),
                 (MeshConfig(fsdp=2, tensor=2, expert=2), "swiglu"),
                 (MeshConfig(fsdp=2, tensor=2, expert=2), "gelu"),
                 # long-context MoE: ring attention over sequence composes
                 # with expert parallelism (the MoE einsums sit outside
                 # ring's shard_map)
                 (MeshConfig(data=2, sequence=2, expert=2), "gelu")],
)
def test_expert_parallel_matches_single_device(mesh, act):
    """The expert-sharded loss/grads equal the unsharded ones — XLA's
    all_to_all dispatch is an execution detail, not a numerical change."""
    from photon_tpu.parallel.context import use_mesh

    cfg = _moe_cfg(mesh)
    cfg.model.moe_mlp_act = act
    if mesh.sequence > 1:
        cfg.model.max_seq_len = 64  # give the ring something to shard
        cfg.model.attn_impl = "ring"
    cfg.validate()
    model = MPTModel(cfg.model)
    params = init_params(cfg.model, seed=0)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, cfg.model.max_seq_len), 0, 64)
    loss_fn = make_loss_fn(model, 2048)
    l_ref, g_ref = jax.value_and_grad(loss_fn)(params, tokens)

    m = make_mesh(cfg.mesh)
    tx = optax.sgd(1.0)
    st = init_train_state(model, tx, params)
    sh = state_shardings(st, m)
    ps = jax.tree.map(lambda l, s: jax.device_put(l, s), st.params, sh.params)
    tok_s = jax.device_put(tokens, NamedSharding(m, batch_spec(m)))
    with use_mesh(m):
        l_sh, g_sh = jax.jit(jax.value_and_grad(loss_fn))(ps, tok_s)
    assert float(l_sh) == pytest.approx(float(l_ref), abs=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5
        ),
        jax.device_get(g_sh), g_ref,
    )


def test_moe_param_specs():
    cfg = _moe_cfg(MeshConfig(expert=4))
    params = init_params(cfg.model, seed=0)
    specs = param_specs(params, make_mesh(cfg.mesh))
    blk = specs["blocks"]["block"]
    assert blk["moe_up"][1] == "expert"
    assert blk["moe_down"][1] == "expert"


def test_moe_validation():
    with pytest.raises(ValueError, match="moe_num_experts >= 2"):
        cfg = _moe_cfg(MeshConfig())
        cfg.model.moe_num_experts = 1
        cfg.validate()
    with pytest.raises(ValueError, match="divisible by mesh.expert"):
        cfg = Config()
        cfg.model.mlp = "moe"
        cfg.model.moe_num_experts = 4
        cfg.mesh = MeshConfig(expert=3)
        cfg.validate()
    with pytest.raises(ValueError, match="requires model.mlp='moe'"):
        cfg = Config()
        cfg.mesh = MeshConfig(expert=2)
        cfg.validate()
    # moe x pipe is now supported (aux collected through the stage scan,
    # tests/test_pipeline.py::test_pipeline_matches_with_moe); the compound
    # batch-axis rule still applies and is covered in test_pipeline


def test_moe_aux_loss_reaches_training_loss():
    """The Switch aux term is part of the training objective: zeroing its
    weight changes the loss value."""
    cfg = _moe_cfg(MeshConfig())
    model = MPTModel(cfg.model)
    params = init_params(cfg.model, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    with_aux = float(make_loss_fn(model, 2048)(params, tokens))
    cfg.model.moe_aux_weight = 0.0
    without = float(make_loss_fn(MPTModel(cfg.model), 2048)(params, tokens))
    assert with_aux > without


def test_moe_prefill_padding_claims_no_capacity():
    """Right-padding must not displace real tokens from expert buffers:
    at TIGHT capacity, a row's prefill logits are identical whether the
    batch carries 3 or 11 padding columns (`route(token_mask=...)`)."""
    from photon_tpu.models.decode import prefill

    cfg = _moe_cfg(MeshConfig())
    cfg.model.moe_capacity_factor = 1.0  # tight: pad tokens would displace
    cfg.validate()
    from photon_tpu.models.mpt import init_params as ip

    params = ip(cfg.model, seed=0)
    rng = np.random.default_rng(0)
    rows = rng.integers(1, 64, (2, 5)).astype(np.int32)
    lengths = jnp.asarray([5, 3])

    def run(pad_to):
        toks = np.zeros((2, pad_to), np.int32)
        toks[:, :5] = rows
        toks[1, 3:] = 0
        logits, _ = prefill(params, jnp.asarray(toks), lengths, cfg.model)
        return np.asarray(logits)

    np.testing.assert_allclose(run(8), run(16), atol=1e-5)


def test_moe_trains_and_capacity_is_static():
    from photon_tpu.train.train_step import make_train_step

    cfg = _moe_cfg(MeshConfig())
    model = MPTModel(cfg.model)
    tx = optax.adam(1e-2)
    st = init_train_state(model, tx, init_params(cfg.model, seed=0))
    step = jax.jit(make_train_step(model, tx, n_microbatches=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    losses = [float(step(st, tokens)[1]["loss"])]
    for _ in range(10):
        st, m = step(st, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert expert_capacity(64, 4, 2, 1.25) == 40  # ceil(2*64*1.25/4)
