"""HF → photon-tpu import: the inverse of the export mapping.

Round-trip property: export a llama-family model to an HF directory, import
it back, and (a) every leaf is bit-identical, (b) logits from the imported
tree match the original model. Plus: importing a checkpoint written by
transformers itself (save_pretrained, safetensors) — the real inbound
format for public llama checkpoints.
"""

import json

import numpy as np
import pytest

from photon_tpu.config.schema import Config

from tests._helpers import tiny_llama_config

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.mark.parametrize("n_kv", [0, 2], ids=["mha", "gqa"])
def test_export_import_roundtrip_bit_identical(tmp_path, n_kv):
    import jax

    from photon_tpu.checkpoint.hf_export import save_hf_llama
    from photon_tpu.checkpoint.hf_import import load_hf_llama
    from photon_tpu.models.mpt import init_params

    cfg = tiny_llama_config(n_kv)
    params = init_params(cfg.model, seed=5)
    out = save_hf_llama(params, cfg.model, str(tmp_path / "hf"))
    derived, imported = load_hf_llama(str(out))

    assert derived.n_kv_heads == cfg.model.n_kv_heads
    assert derived.mlp_hidden_size == 48 and derived.rope

    orig_leaves = jax.tree_util.tree_leaves_with_path(params)
    imp_flat = dict(jax.tree_util.tree_leaves_with_path(imported))
    assert len(orig_leaves) == len(imp_flat)
    for path, leaf in orig_leaves:
        np.testing.assert_array_equal(np.asarray(leaf), imp_flat[path], err_msg=str(path))


def test_mixtral_export_import_roundtrip_bit_identical(tmp_path):
    """Our mixtral export feeds our mixtral import: the param tree comes
    back bit-identical and the derived config carries the MoE knobs."""
    import jax

    from photon_tpu.checkpoint.hf_export import save_hf_mixtral
    from photon_tpu.checkpoint.hf_import import load_hf_llama
    from photon_tpu.models.mpt import init_params

    cfg = tiny_llama_config(2)
    cfg.model.mlp = "moe"
    cfg.model.moe_mlp_act = "swiglu"
    cfg.model.moe_num_experts = 4
    cfg.model.moe_top_k = 2
    cfg.validate()
    params = init_params(cfg.model, seed=5)
    out = save_hf_mixtral(params, cfg.model, str(tmp_path / "hf"))
    derived, imported = load_hf_llama(str(out))

    assert derived.mlp == "moe" and derived.moe_mlp_act == "swiglu"
    assert derived.moe_num_experts == 4 and derived.moe_top_k == 2
    assert derived.moe_capacity_factor == 2.0  # E/k: drop-free like HF

    orig_leaves = jax.tree_util.tree_leaves_with_path(params)
    imp_flat = dict(jax.tree_util.tree_leaves_with_path(imported))
    assert len(orig_leaves) == len(imp_flat)
    for path, leaf in orig_leaves:
        np.testing.assert_array_equal(np.asarray(leaf), imp_flat[path], err_msg=str(path))


def test_mixtral_import_from_transformers_save_pretrained(tmp_path):
    """A checkpoint WRITTEN BY transformers' MixtralForCausalLM imports and
    produces the same logits in our forward — the genuine external inbound path."""
    from photon_tpu.checkpoint.hf_import import load_hf_llama
    from photon_tpu.models.mpt import MPTModel

    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=16, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2,
        tie_word_embeddings=False, torch_dtype="float32",
    )
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(hf_cfg)
    hf.eval()
    hf.save_pretrained(str(tmp_path / "hf"))

    derived, params = load_hf_llama(str(tmp_path / "hf"))
    derived.attn_impl = "xla"
    derived.compute_dtype = "float32"
    model = MPTModel(derived)
    tokens = np.random.default_rng(0).integers(0, 96, (2, 12), dtype=np.int32)
    ours = np.asarray(model.apply({"params": params}, tokens))
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_import_from_transformers_save_pretrained(tmp_path):
    """A checkpoint written by transformers itself (safetensors) imports and
    produces the same logits through OUR model as through HF."""
    from photon_tpu.checkpoint.hf_import import load_hf_llama
    from photon_tpu.models.mpt import MPTModel

    hf_cfg = transformers.LlamaConfig(
        hidden_size=32, intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=16,
        vocab_size=96, rope_theta=10000.0, rms_norm_eps=1e-5,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
        torch_dtype="float32",
    )
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    hf.save_pretrained(str(tmp_path / "hf"))
    assert (tmp_path / "hf" / "model.safetensors").exists()

    model_cfg, params = load_hf_llama(str(tmp_path / "hf"))
    model_cfg.attn_impl = "xla"
    model_cfg.compute_dtype = "float32"
    model = MPTModel(model_cfg)
    tokens = np.random.default_rng(1).integers(0, 96, (2, 12), dtype=np.int32)
    ours = np.asarray(model.apply({"params": params}, tokens))
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_import_sharded_safetensors(tmp_path):
    """Index-file checkpoints (the format large public llamas actually ship
    in) load through the shard-merging path."""
    from photon_tpu.checkpoint.hf_import import load_hf_llama

    hf_cfg = transformers.LlamaConfig(
        hidden_size=32, intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=16, vocab_size=96,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
    )
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.save_pretrained(str(tmp_path / "hf"), max_shard_size="20KB")
    index = tmp_path / "hf" / "model.safetensors.index.json"
    assert index.exists(), "test setup: shard size did not force an index"
    n_shards = len(set(json.loads(index.read_text())["weight_map"].values()))
    assert n_shards > 1

    model_cfg, params = load_hf_llama(str(tmp_path / "hf"))
    got = np.asarray(params["wte"]["embedding"])
    want = hf.model.embed_tokens.weight.detach().numpy()
    np.testing.assert_array_equal(got, want)
    assert model_cfg.n_layers == 2


def test_import_cli_writes_npz_and_yaml(tmp_path):
    from photon_tpu.checkpoint import npz_to_arrays
    from photon_tpu.checkpoint.hf_export import save_hf_llama
    from photon_tpu.checkpoint.hf_import import main
    from photon_tpu.models.mpt import init_params

    cfg = tiny_llama_config()
    params = init_params(cfg.model, seed=2)
    save_hf_llama(params, cfg.model, str(tmp_path / "hf"))
    out = tmp_path / "imported.npz"
    main(["--hf-dir", str(tmp_path / "hf"), "--out", str(out)])
    meta, arrays = npz_to_arrays(out.read_bytes())
    assert meta.n_arrays == 10  # MHA tree: fused wqkv (GQA would be 12)
    assert (tmp_path / "imported.model.yaml").exists()


def test_import_rejects_mismatched_config(tmp_path):
    from photon_tpu.checkpoint.hf_export import save_hf_llama
    from photon_tpu.checkpoint.hf_import import load_hf_llama
    from photon_tpu.models.mpt import init_params

    cfg = tiny_llama_config()
    save_hf_llama(init_params(cfg.model, seed=0), cfg.model, str(tmp_path / "hf"))
    wrong = tiny_llama_config()
    wrong.model.n_layers = 3
    with pytest.raises(ValueError, match="config mismatch"):
        load_hf_llama(str(tmp_path / "hf"), wrong.model)


def test_import_rejects_tied_and_biased(tmp_path):
    from photon_tpu.checkpoint.hf_import import model_config_from_hf

    base = dict(model_type="llama", hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, max_position_embeddings=16,
                vocab_size=96, intermediate_size=48)
    with pytest.raises(ValueError, match="tied"):
        model_config_from_hf({**base, "tie_word_embeddings": True})
    with pytest.raises(ValueError, match="biased"):
        model_config_from_hf({**base, "attention_bias": True})
    with pytest.raises(ValueError, match="model_type"):
        model_config_from_hf({**base, "model_type": "mistral"})
    with pytest.raises(ValueError, match="rope_scaling"):
        model_config_from_hf(
            {**base, "rope_scaling": {"rope_type": "llama3", "factor": 8.0}}
        )


def test_import_threads_norm_eps():
    """rms_norm_eps from the checkpoint lands in the model config (and the
    model's norms read it) instead of being silently pinned to 1e-5."""
    from photon_tpu.checkpoint.hf_import import model_config_from_hf

    m = model_config_from_hf(dict(
        model_type="llama", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=16,
        vocab_size=96, intermediate_size=48, rms_norm_eps=1e-6,
    ))
    assert m.norm_eps == pytest.approx(1e-6)
