import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.config.schema import ModelConfig, OptimizerConfig, SchedulerConfig
from photon_tpu.models.mpt import MPTModel, init_params
from photon_tpu.optim import build_optimizer, build_schedule
from photon_tpu.train import init_train_state, make_eval_step, make_train_step

TINY = ModelConfig(
    d_model=64, n_layers=2, n_heads=4, max_seq_len=32, vocab_size=64,
    attn_impl="xla", compute_dtype="float32",
)


def _setup(opt_name="adamw", n_micro=1):
    ocfg = OptimizerConfig(name=opt_name, lr=1e-3)
    scfg = SchedulerConfig(t_warmup=2, t_max=50)
    tx, sched = build_optimizer(ocfg, scfg)
    model = MPTModel(TINY)
    params = init_params(TINY, seed=0)
    state = init_train_state(model, tx, params)
    step = jax.jit(make_train_step(model, tx, n_microbatches=n_micro))
    return model, state, step, sched


def _batch(key, b=4, s=32):
    return jax.random.randint(key, (b, s), 0, TINY.vocab_size)


def test_loss_decreases_adamw():
    _, state, step, _ = _setup("adamw")
    tokens = _batch(jax.random.PRNGKey(0))
    losses = []
    for _ in range(20):
        state, m = step(state, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_loss_decreases_adopt():
    _, state, step, _ = _setup("adopt")
    tokens = _batch(jax.random.PRNGKey(0))
    losses = []
    for _ in range(20):
        state, m = step(state, tokens)
        losses.append(float(m["loss"]))
    # ADOPT step 0 only initializes v; still must learn overall
    assert losses[-1] < losses[0] - 0.5, losses


def test_microbatching_matches_full_batch():
    """Grad accumulation must be numerically equivalent to the full batch."""
    _, state, step_full, _ = _setup("adamw", n_micro=1)
    _, state2, step_micro, _ = _setup("adamw", n_micro=4)
    tokens = _batch(jax.random.PRNGKey(1), b=8)
    s1, m1 = step_full(state, tokens)
    s2, m2 = step_micro(state2, tokens)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_schedule_shape():
    sched = build_schedule(SchedulerConfig(t_warmup=10, t_max=100, alpha_f=0.1), base_lr=1.0)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 0.1, rtol=1e-6)
    assert float(sched(55)) > float(sched(90))


def test_eval_step():
    model, state, step, _ = _setup()
    eval_step = jax.jit(make_eval_step(model))
    tokens = _batch(jax.random.PRNGKey(2))
    ce_sum, n = eval_step(state.params, tokens)
    assert n == tokens.shape[0] * (tokens.shape[1] - 1)
    assert np.isfinite(float(ce_sum))


def test_determinism():
    _, state, step, _ = _setup()
    tokens = _batch(jax.random.PRNGKey(3))
    s1, m1 = step(state, tokens)
    s2, m2 = step(state, tokens)
    assert float(m1["loss"]) == float(m2["loss"])
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
