"""Serving plane (ISSUE 5): paged cache parity, scheduler invariants, HTTP.

Three layers of contract:

1. the paged KV cache decodes BIT-EXACTLY like the contiguous
   ``models/decode.py`` path (logits compared with assert_array_equal
   across MPT/wpe, MPT/ALiBi and llama/RoPE/GQA configs);
2. the continuous batcher leaks nothing under randomized arrival/length
   streams (slots, blocks, FIFO order, queue bound);
3. the stdlib HTTP frontend streams exactly what the offline decoder
   produces for the same checkpoint.
"""

import http.client
import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import Config

from tests._helpers import tiny_llama_config


def _serve_cfg(*, alibi=False, llama=False, n_slots=2, block_size=4,
               max_seq=32, max_new=8) -> Config:
    if llama:
        cfg = tiny_llama_config(n_kv_heads=2)
    else:
        cfg = Config()
        cfg.model.d_model = 32
        cfg.model.n_layers = 2
        cfg.model.n_heads = 4
        cfg.model.vocab_size = 96
        cfg.model.attn_impl = "xla"
        cfg.model.compute_dtype = "float32"
        cfg.model.alibi = alibi
        cfg.model.learned_pos_emb = not alibi
    cfg.model.max_seq_len = max_seq
    cfg.photon.serve.n_slots = n_slots
    cfg.photon.serve.block_size = block_size
    cfg.photon.serve.max_new_tokens = max_new
    return cfg.validate()


def _ragged_prompts(rng, n, vocab, lo=3, hi=10):
    return [list(map(int, rng.integers(1, vocab, rng.integers(lo, hi))))
            for _ in range(n)]


def _offline_greedy(cfg, params, prompt, n):
    """Oracle: the contiguous cached decoder, one row."""
    from photon_tpu.models.decode import make_cached_generate_fn

    buf = np.zeros((1, len(prompt) + n), np.int32)
    buf[0, : len(prompt)] = prompt
    fn = make_cached_generate_fn(cfg.model, params)
    t, _ = fn.many(jnp.asarray(buf), jnp.asarray([len(prompt)], np.int32), n)
    return [int(x) for x in np.asarray(t)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# 1. paged cache vs contiguous DecodeState — bit-exact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["mpt-wpe", "mpt-alibi", "llama-gqa"])
def test_paged_decode_bitexact_with_contiguous(name):
    from photon_tpu.models.decode import decode_step, prefill
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.cache import (
        BlockAllocator, init_paged_state, paged_decode_step, write_prefill_blocks,
    )

    cfg = _serve_cfg(alibi=name == "mpt-alibi", llama=name == "llama-gqa")
    mc = cfg.model
    params = init_params(mc, seed=4)
    b, s, gen, bs = 3, 16, 6, 4
    max_blocks = s // bs  # paged S_cap == contiguous S → comparable shapes
    rng = np.random.default_rng(1)
    lengths = np.asarray([4, 7, 10], np.int32)
    tokens = np.zeros((b, s), np.int32)
    for i, ln in enumerate(lengths):
        tokens[i, :ln] = rng.integers(1, mc.vocab_size, ln)

    logits_c, st = prefill(params, jnp.asarray(tokens), jnp.asarray(lengths), mc)

    alloc = BlockAllocator(b * max_blocks)
    pst = init_paged_state(mc, b, b * max_blocks, bs, max_blocks)
    for i in range(b):
        pst = write_prefill_blocks(pst, i, alloc.alloc(max_blocks),
                                   st.cache_k[:, i:i + 1], st.cache_v[:, i:i + 1],
                                   int(lengths[i]))
    active = jnp.ones(b, bool)
    logits_p = logits_c  # prefill logits ARE the contiguous ones by construction
    for _ in range(gen):
        nxt = jnp.argmax(logits_c, axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(  # every step: identical logits, bitwise
            np.asarray(logits_p), np.asarray(logits_c))
        logits_c, st = decode_step(params, st, nxt, mc)
        logits_p, pst = paged_decode_step(params, pst, nxt, mc, active)
    np.testing.assert_array_equal(np.asarray(logits_p), np.asarray(logits_c))
    np.testing.assert_array_equal(np.asarray(pst.lengths),
                                  np.asarray(st.lengths))


def test_block_allocator_guards():
    from photon_tpu.serve.cache import BlockAllocator, BlockLeakError

    a = BlockAllocator(4)
    ids = a.alloc(3)
    assert a.free_blocks == 1 and a.alloc(2) is None  # no partial allocation
    a.free(ids)
    assert a.free_blocks == 4
    with pytest.raises(BlockLeakError):
        a.free(ids[:1])  # double free
    b = a.alloc(4)
    assert a.alloc(1) is None and sorted(b) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# 2. engine + continuous batcher
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    """One tiny MPT engine + batcher shared by the behavioral tests (module
    scope: the jit compiles dominate; state fully drains between tests).

    The whole fixture lifetime runs under the photon-lint lock-order
    recorder (ISSUE 6): every lock the engine/batcher/frontend creates is
    tracked, and teardown fails on any acquisition-order cycle observed
    across ALL the behavioral tests — a potential deadlock between the
    scheduler loop, submitters, and the telemetry plane."""
    from photon_tpu.analysis import runtime as lint_rt
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    recorder = lint_rt.install_lock_order()
    try:
        cfg = _serve_cfg(n_slots=2, block_size=4, max_seq=32, max_new=8)
        params = init_params(cfg.model, seed=4)
        engine = PagedEngine(cfg, params)
        batcher = ContinuousBatcher(engine, max_queue=64).start()
        yield cfg, params, engine, batcher
        batcher.close()
        recorder.check()  # green = no lock-order inversion anywhere above
    finally:
        lint_rt.uninstall_lock_order()


def _assert_drained(engine, batcher):
    assert engine.n_active == 0, "slot leak"
    assert engine.free_blocks == engine.n_blocks, "block leak"
    assert batcher.queue_depth == 0


def test_continuous_batching_matches_offline_greedy(served):
    cfg, params, engine, batcher = served
    rng = np.random.default_rng(0)
    prompts = _ragged_prompts(rng, 5, cfg.model.vocab_size)
    reqs = [batcher.submit(p, 6) for p in prompts]
    outs = [r.result(timeout=60) for r in reqs]
    for p, got in zip(prompts, outs):
        assert got == _offline_greedy(cfg, params, p, 6), p
    _assert_drained(engine, batcher)


def test_eos_evicts_early_and_recycles(served):
    cfg, params, engine, batcher = served
    rng = np.random.default_rng(3)
    prompts = _ragged_prompts(rng, 4, cfg.model.vocab_size)
    # offline tells us each prompt's greedy stream; use its SECOND token as
    # that request's EOS: the server must stop at the FIRST occurrence of
    # that id (which may be earlier, if the stream repeats a token)
    for p in prompts:
        want = _offline_greedy(cfg, params, p, 6)
        eos = want[1]
        req = batcher.submit(p, 6, eos_id=eos)
        got = req.result(timeout=60)
        assert got == want[: want.index(eos) + 1], (got, want)
        assert len(got) < 6  # actually exited early
    _assert_drained(engine, batcher)
    assert batcher.evictions >= 4


def test_seeded_sampling_reproduces(served):
    cfg, params, engine, batcher = served
    prompt = [5, 9, 2, 7]
    a = batcher.submit(prompt, 6, temperature=1.0, seed=11).result(timeout=60)
    b = batcher.submit(prompt, 6, temperature=1.0, seed=11).result(timeout=60)
    g = batcher.submit(prompt, 6, temperature=0.0, seed=99).result(timeout=60)
    assert a == b  # same seed, same stream — independent of batch-mates
    assert g == _offline_greedy(cfg, params, prompt, 6)  # temp 0 stays greedy
    _assert_drained(engine, batcher)


def test_scheduler_invariants_random_streams(served):
    """Property test: randomized arrival/length streams; afterwards no slot
    leak, no block leak, admission strictly FIFO, queue bounded."""
    cfg, params, engine, batcher = served
    rng = np.random.default_rng(7)
    before = list(batcher.admitted_order)
    reqs = []
    for _ in range(12):
        p = _ragged_prompts(rng, 1, cfg.model.vocab_size, lo=2, hi=12)[0]
        n = int(rng.integers(1, 8))
        reqs.append(batcher.submit(p, n))
    outs = [r.result(timeout=120) for r in reqs]
    for r, out in zip(reqs, outs):
        assert 1 <= len(out) <= r.max_new_tokens
        assert out == _offline_greedy(cfg, params, r.prompt, len(out))
    admitted = list(batcher.admitted_order)[len(before):]
    assert admitted == sorted(admitted), "admission overtook FIFO order"
    _assert_drained(engine, batcher)


def test_failed_admission_is_transactional(served):
    """A chunk-step blow-up mid-prefill fails the in-flight request (the
    client gets the error, not a timeout), leaks no blocks — the failure
    is injected at the engine's device-call seam, BEFORE the donated
    state is consumed, so the engine survives — and the server keeps
    serving the queue afterwards."""
    cfg, params, engine, batcher = served
    real = engine._mixed_call
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("injected prefill failure")

    engine._mixed_call = boom
    try:
        req = batcher.submit([5, 9, 2], 4)
        with pytest.raises(RuntimeError, match="injected prefill failure"):
            req.result(timeout=60)
    finally:
        engine._mixed_call = real
    assert calls["n"] >= 1
    _assert_drained(engine, batcher)
    ok = batcher.submit([5, 9, 2], 4).result(timeout=60)  # still serving
    assert ok == _offline_greedy(cfg, params, [5, 9, 2], 4)
    _assert_drained(engine, batcher)


def test_steady_state_serving_never_retraces(served):
    """ISSUE 6 e2e wiring: with the engine warm (every prefill bucket and
    the decode step already compiled by the tests above), the photon-lint
    retrace sentinel rides a fresh burst of ragged traffic — the scheduler
    loop's ``steady_point("serve/tick")`` hook bills any compile to its
    tick, and ANY compile fails. This is PR 5's "admission never retraces"
    contract, machine-checked instead of argued."""
    from photon_tpu.analysis import runtime as lint_rt

    cfg, params, engine, batcher = served
    rng = np.random.default_rng(21)
    prompts = _ragged_prompts(rng, 6, cfg.model.vocab_size, lo=2, hi=12)
    budgets = [int(rng.integers(1, 8)) for _ in prompts]
    # warmup burst: the SAME stream first runs unguarded, so this test owns
    # its compiles and stays green under -k / --lf / reordering instead of
    # leaning on earlier tests having warmed the prefill buckets
    for r in [batcher.submit(p, n) for p, n in zip(prompts, budgets)]:
        r.result(timeout=120)
    with lint_rt.retrace_guard(steady=True) as sentinel:
        reqs = [batcher.submit(p, n) for p, n in zip(prompts, budgets)]
        outs = [r.result(timeout=120) for r in reqs]
    assert sentinel.violations == []
    # ... and again with CHUNKED prefill (ISSUE 12): a small per-step
    # token budget splits every prompt into multi-chunk mixed batches.
    # The chunk widths depend only on each prompt's length and the
    # budget, so one unguarded warm pass covers every (Tq, n_ctx)
    # bucket the guarded pass can produce
    batcher.prefill_token_budget = 3
    try:
        for r in [batcher.submit(p, n) for p, n in zip(prompts, budgets)]:
            r.result(timeout=120)  # warm the chunk buckets
        with lint_rt.retrace_guard(steady=True) as sentinel:
            reqs2 = [batcher.submit(p, n) for p, n in zip(prompts, budgets)]
            for r in reqs2:
                r.result(timeout=120)
        assert sentinel.violations == []
        assert batcher.chunk_split_prompts > 0  # chunking genuinely happened
    finally:
        batcher.prefill_token_budget = 2048
    # the offline oracle runs OUTSIDE the guard: its contiguous decode
    # buffers are shaped per (prompt+n) and legitimately compile fresh
    for p, out in zip(prompts, outs):
        assert out == _offline_greedy(cfg, params, p, len(out))
    _assert_drained(engine, batcher)


def test_queue_backpressure_rejects():
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher, QueueFullError

    cfg = _serve_cfg(n_slots=1, block_size=4, max_seq=32, max_new=8)
    engine = PagedEngine(cfg, init_params(cfg.model, seed=0))
    batcher = ContinuousBatcher(engine, max_queue=2)  # NOT started: queue only fills
    try:
        batcher.submit([1, 2, 3], 4)
        batcher.submit([1, 2, 3], 4)
        with pytest.raises(QueueFullError):
            batcher.submit([1, 2, 3], 4)
        assert batcher.rejected == 1
        with pytest.raises(ValueError, match="context capacity"):
            batcher.submit(list(range(1, 40)), 8)  # can never fit → immediate 400
    finally:
        batcher.close()


def test_oversized_request_rejected_for_small_pool():
    """A request whose reservation exceeds the (user-shrunk) POOL must be
    rejected at submit — otherwise it would FIFO head-block the queue
    forever behind a can_admit() that can never pass."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=1, block_size=4, max_seq=32, max_new=8)
    cfg.photon.serve.n_blocks = 2  # pool holds 8 tokens total
    engine = PagedEngine(cfg, init_params(cfg.model, seed=0))
    batcher = ContinuousBatcher(engine, max_queue=4).start()
    try:
        with pytest.raises(ValueError, match="context capacity"):
            batcher.submit([1, 2, 3, 4, 5], 8)  # needs 4 blocks > pool of 2
        ok = batcher.submit([1, 2, 3], 4).result(timeout=60)  # 2 blocks: fits
        assert len(ok) == 4
        _assert_drained(engine, batcher)
    finally:
        batcher.close()


def test_batch_synchronous_baseline_waves():
    """The bench baseline: admission waits for the whole wave to finish, so
    the second wave's admit time is after the first wave's completions."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=2, block_size=4, max_seq=32, max_new=8)
    engine = PagedEngine(cfg, init_params(cfg.model, seed=0))
    batcher = ContinuousBatcher(engine, max_queue=16, batch_synchronous=True).start()
    try:
        reqs = [batcher.submit([1 + i, 2, 3], 4) for i in range(4)]
        for r in reqs:
            r.result(timeout=60)
        # a wave fills ALL slots before decoding (not one-at-a-time serial):
        # both wave-1 members are admitted before either finishes
        assert max(r.t_admit for r in reqs[:2]) <= min(r.t_done for r in reqs[:2])
        wave1_done = max(r.t_done for r in reqs[:2])
        assert min(r.t_admit for r in reqs[2:]) >= wave1_done
        _assert_drained(engine, batcher)
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# 3. checkpoint → engine → HTTP e2e
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def http_server(tmp_path_factory):
    """A real round checkpoint served over HTTP (module scope)."""
    from photon_tpu.checkpoint import FileStore
    from photon_tpu.checkpoint.server import ServerCheckpointManager
    from photon_tpu.codec import params_to_ndarrays
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.frontend import ServeFrontend
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=2, block_size=4, max_seq=32, max_new=8)
    cfg.run_uuid = "serve-e2e"
    params = init_params(cfg.model, seed=4)
    store = FileStore(tmp_path_factory.mktemp("serve-store"))
    mgr = ServerCheckpointManager(store, cfg.run_uuid)
    meta, arrays = params_to_ndarrays(params)
    mgr.save_round(3, meta, arrays, server_state={"server_round": 3})

    engine = PagedEngine.from_checkpoint(cfg, store=store, resume_round=-1)
    assert engine.loaded_round == 3
    batcher = ContinuousBatcher(engine, max_queue=8).start()
    fe = ServeFrontend(batcher, max_new_tokens_cap=8)
    port = fe.start()
    yield cfg, params, engine, batcher, port
    fe.close()
    batcher.close()


def _http(port):
    return http.client.HTTPConnection("127.0.0.1", port, timeout=60)


def test_http_blocking_matches_offline(http_server):
    cfg, params, engine, batcher, port = http_server
    prompt = [5, 9, 2, 7, 1]
    c = _http(port)
    c.request("POST", "/generate",
              json.dumps({"tokens": prompt, "max_new_tokens": 6}))
    r = c.getresponse()
    body = json.loads(r.read())
    assert r.status == 200, body
    assert body["tokens"] == _offline_greedy(cfg, params, prompt, 6)
    assert body["n_prompt"] == 5 and body["ttft_s"] >= 0.0


def test_http_streaming_matches_offline(http_server):
    cfg, params, engine, batcher, port = http_server
    prompt = [3, 3, 8, 1]
    c = _http(port)
    c.request("POST", "/generate",
              json.dumps({"tokens": prompt, "max_new_tokens": 6, "stream": True}))
    r = c.getresponse()
    assert r.status == 200
    lines = r.read().decode().strip().splitlines()
    toks = [json.loads(ln)["token"] for ln in lines[:-1]]
    final = json.loads(lines[-1])
    assert final["done"] is True and final["tokens"] == toks
    assert toks == _offline_greedy(cfg, params, prompt, 6)


def test_http_healthz_metrics_and_errors(http_server):
    cfg, params, engine, batcher, port = http_server
    c = _http(port)
    c.request("GET", "/healthz")
    h = json.loads(c.getresponse().read())
    assert h["status"] == "ok" and h["round"] == 3
    c.request("GET", "/metrics")
    m = c.getresponse().read().decode()
    assert "photon_serve_queue_depth" in m
    assert "photon_serve_slot_occupancy" in m
    def roundtrip(method, path, body=None):
        # read the body every time — HTTP/1.1 keep-alive reuse demands it
        c.request(method, path, body)
        r = c.getresponse()
        r.read()
        return r.status

    assert roundtrip("POST", "/generate", json.dumps({"max_new_tokens": 4})) == 400
    assert roundtrip("POST", "/generate", "{not json") == 400
    # un-coercible field types are a 400, not a dropped connection
    assert roundtrip("POST", "/generate",
                     json.dumps({"tokens": [1, 2], "eos_id": [5]})) == 400
    assert roundtrip("POST", "/generate",
                     json.dumps({"tokens": [1, "x"]})) == 400
    assert roundtrip("GET", "/nope") == 404


def test_request_spans_emitted(http_server):
    from photon_tpu import telemetry
    from photon_tpu.config.schema import TelemetryConfig
    from photon_tpu.utils.profiling import (
        SERVE_DECODE_SPAN, SERVE_PREFILL_SPAN, SERVE_QUEUE_SPAN, SERVE_REQUEST_SPAN,
    )

    cfg, params, engine, batcher, port = http_server
    tracer = telemetry.install(TelemetryConfig(enabled=True), scope="serve")
    try:
        batcher.submit([5, 9, 2], 3).result(timeout=60)
        spans = tracer.drain()
    finally:
        telemetry.uninstall()
    by_name = {s["name"]: s for s in spans}
    umbrella = by_name[SERVE_REQUEST_SPAN]
    for child in (SERVE_QUEUE_SPAN, SERVE_PREFILL_SPAN, SERVE_DECODE_SPAN):
        assert by_name[child]["parent_id"] == umbrella["span_id"]
        assert by_name[child]["trace_id"] == umbrella["trace_id"]


def test_graceful_drain_zero_dropped_inflight(tmp_path):
    """SIGTERM drain contract (ISSUE 8 satellite): once draining, /healthz
    reports ``draining`` and new /generate gets 503 + Retry-After, while
    everything already accepted — running slots AND queued requests — runs
    to completion within ``serve.drain_timeout_s``. Zero dropped in-flight
    requests across the drain, outputs identical to the offline oracle."""
    import threading
    import time

    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.frontend import ServeFrontend
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=2, block_size=4, max_seq=32, max_new=8)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(engine, max_queue=8).start()
    fe = ServeFrontend(batcher, max_new_tokens_cap=8)
    port = fe.start()
    try:
        # warm the jit caches so in-flight timing is about scheduling
        batcher.submit([5, 9, 2], 3).result(timeout=120)

        # 4 in-flight requests: 2 fill the slots, 2 wait in the queue —
        # the queued ones are "accepted" too and must NOT be dropped
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8], [7, 9, 3, 2]]
        results: list[tuple[int, dict]] = [None] * len(prompts)  # type: ignore[list-item]

        def _post(i: int) -> None:
            c = _http(port)
            c.request("POST", "/generate",
                      json.dumps({"tokens": prompts[i], "max_new_tokens": 8}))
            r = c.getresponse()
            results[i] = (r.status, json.loads(r.read()))

        threads = [threading.Thread(target=_post, args=(i,),
                                    name=f"drain-client-{i}", daemon=True)
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and engine.n_active == 0:
            time.sleep(0.005)
        assert engine.n_active > 0  # requests genuinely in flight

        # the __main__ SIGTERM sequence: flag the edge, then drain the plane
        fe.mark_draining()
        c = _http(port)
        c.request("GET", "/healthz")
        h = c.getresponse()
        assert json.loads(h.read())["status"] == "draining"
        c.request("POST", "/generate",
                  json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 2}))
        r = c.getresponse()
        refused = json.loads(r.read())
        assert r.status == 503, refused
        assert r.getheader("Retry-After") is not None

        assert batcher.drain(cfg.photon.serve.drain_timeout_s) is True
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        # zero dropped: every accepted request completed, bit-identical
        # with the offline oracle
        for p, (status, body) in zip(prompts, results):
            assert status == 200, body
            assert body["tokens"] == _offline_greedy(cfg, params, p, 8), p
        _assert_drained(engine, batcher)
        # post-drain: direct submission refuses cleanly too
        with pytest.raises(Exception):
            batcher.submit([1, 2], 2)
    finally:
        fe.close()
        batcher.close()


def test_serve_kpis_are_registered(http_server):
    """Every KPI the batcher records is a registry constant (the serving
    half of the ISSUE 4 registry contract)."""
    from photon_tpu.utils.profiling import is_registered_metric

    cfg, params, engine, batcher, port = http_server
    batcher.submit([5, 9, 2], 3).result(timeout=60)
    recorded = set(batcher.history.rounds)
    assert recorded, "batcher recorded no KPIs"
    unregistered = sorted(k for k in recorded if not is_registered_metric(k))
    assert not unregistered, unregistered


def test_load_round_params_skips_state(tmp_path):
    """The params-only load path touches ONLY the params object — a missing
    state.bin (never read) doesn't matter, and momenta stay unread."""
    from photon_tpu.checkpoint import FileStore
    from photon_tpu.checkpoint.server import PARAMS_FILE, ServerCheckpointManager
    from photon_tpu.codec import params_to_ndarrays
    from photon_tpu.models.mpt import init_params

    cfg = _serve_cfg()
    params = init_params(cfg.model, seed=1)
    store = FileStore(tmp_path)
    mgr = ServerCheckpointManager(store, "r")
    meta, arrays = params_to_ndarrays(params)
    mgr.save_round(1, meta, arrays, strategy_state={"momenta": arrays},
                   server_state={"server_round": 1})
    reads: list[str] = []
    orig_get = store.get
    store.get = lambda k: (reads.append(k), orig_get(k))[1]
    meta2, arrays2 = mgr.load_round_params(1)
    assert meta2.names == meta.names
    for a, b in zip(arrays, arrays2):
        np.testing.assert_array_equal(a, b)
    assert all(k.endswith(PARAMS_FILE) for k in reads), reads
