"""SLO autopilot (ISSUE 19): reducers, knobs, controller, storm e2e.

Four layers of contract:

1. the windowed reducers on every hub instrument (``percentile``,
   ``rate``, ``slope``, ``ewma``) pin EXACT values on hand-built series
   under an injected clock;
2. every runtime-mutable knob rejects out-of-range values loudly
   (budget, spec K ceiling, stage timeout, staleness bound) and the
   pressure-safe reclaim paths skip pinned state;
3. the controller itself — breach → actuate once per cooldown,
   hysteresis relax toward the declared optimum, one ``saturated`` per
   episode, the enum quantization ladder, async reject-rate widening,
   the HBM alert-latch reclaim, per-replica restart cooldown — all on an
   injected clock, with every decision on the ring + event log;
4. a seeded chaos storm through the REAL scheduler (slow-marked): the
   controller must shrink the prefill budget under induced queue
   saturation and surface the decision at /statusz.
"""

import threading

import numpy as np
import pytest

from photon_tpu import chaos, telemetry
from photon_tpu.config.schema import (
    AutopilotConfig,
    ChaosConfig,
    Config,
    TelemetryConfig,
)
from photon_tpu.telemetry.autopilot import Autopilot
from photon_tpu.telemetry.health import HealthMonitor
from photon_tpu.telemetry.metrics import MetricsHub
from photon_tpu.utils.profiling import (
    ALERT_HBM_GROWTH,
    AUTOPILOT_ACTION_RECLAIM,
    AUTOPILOT_ACTION_RESTART,
    AUTOPILOT_KNOB_MAX_STALENESS,
    AUTOPILOT_KNOB_PREFILL_BUDGET,
    AUTOPILOT_KNOB_QUANT_LEVEL,
    COLLECTIVE_WIRE_BYTES,
    EVENT_AUTOPILOT_ACTUATION,
    EVENT_AUTOPILOT_RELAX,
    EVENT_AUTOPILOT_SATURATED,
    SERVE_QUEUE_DEPTH,
)


class _Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_planes():
    telemetry.uninstall()
    chaos.uninstall()
    yield
    telemetry.uninstall()
    chaos.uninstall()


def _install(clk, **ap_kw):
    """Telemetry plane + injected-clock hub/health + a controller built on
    the same clock (the config-install path is covered separately)."""
    telemetry.install(TelemetryConfig(enabled=True), scope="t")
    telemetry._METRICS = MetricsHub(clock=clk)
    telemetry._HEALTH = HealthMonitor(clock=clk)
    ap = Autopilot(AutopilotConfig(enabled=True, **ap_kw), clock=clk)
    telemetry._AUTOPILOT = ap
    return ap


def _event_kinds():
    return [e["kind"] for e in telemetry.drain_events()]


# ---------------------------------------------------------------------------
# 1. windowed reducers — exact values on hand-built series
# ---------------------------------------------------------------------------


def test_percentile_nearest_rank_exact():
    clk = _Clock(0.0)
    hub = MetricsHub(clock=clk)
    g = hub.gauge(SERVE_QUEUE_DEPTH)
    for i in range(10):
        clk.t = float(i)
        g.set(float(i))
    clk.t = 9.0
    assert g.percentile(0.0) == 0.0
    assert g.percentile(0.5) == 5.0  # int(0.5*9 + 0.5) == 5
    assert g.percentile(0.9) == 8.0  # int(0.9*9 + 0.5) == 8
    assert g.percentile(1.0) == 9.0
    # trailing 4.5 s window keeps ts >= 4.5 → values 5..9, p50 == 7
    assert g.percentile(0.5, window_s=4.5) == 7.0


def test_rate_endpoint_delta_exact():
    clk = _Clock(0.0)
    hub = MetricsHub(clock=clk)
    c = hub.counter(COLLECTIVE_WIRE_BYTES)
    c.inc(0.0)
    clk.t = 10.0
    c.inc(50.0)
    assert c.rate() == 5.0
    assert c.latest() == 50.0


def test_slope_least_squares_exact():
    clk = _Clock(0.0)
    hub = MetricsHub(clock=clk)
    g = hub.gauge(SERVE_QUEUE_DEPTH)
    for i in range(5):
        clk.t = float(i)
        g.set(1.0 + 2.0 * i)  # exact line: slope must be exactly 2
    assert g.slope() == 2.0
    # a window catching only the last two samples sees the same line
    assert g.slope(window_s=1.0) == 2.0


def test_ewma_seeded_from_first_sample():
    clk = _Clock(0.0)
    hub = MetricsHub(clock=clk)
    g = hub.gauge(SERVE_QUEUE_DEPTH)
    g.set(0.0)
    clk.t = 1.0
    g.set(10.0)
    assert g.ewma(alpha=0.5) == 5.0  # 0 + 0.5*(10-0)
    # window that excludes the first sample re-seeds from the second
    assert g.ewma(alpha=0.5, window_s=0.5) == 10.0


def test_reducers_empty_and_degenerate_windows_are_none():
    clk = _Clock(0.0)
    hub = MetricsHub(clock=clk)
    g = hub.gauge(SERVE_QUEUE_DEPTH)
    assert g.latest() is None
    assert g.percentile(0.5) is None
    assert g.rate() is None
    assert g.slope() is None
    assert g.ewma() is None
    g.set(3.0)
    assert g.rate() is None  # one sample: no timespan
    assert g.slope() is None
    g.set(4.0)
    assert g.rate() is None  # zero timespan between samples
    assert g.slope() is None  # zero time variance


# ---------------------------------------------------------------------------
# 2. runtime-mutable knobs — loud rejects, pressure-safe reclaim
# ---------------------------------------------------------------------------


def test_batcher_budget_setter_rejects_below_one():
    from photon_tpu.serve.scheduler import ContinuousBatcher

    b = object.__new__(ContinuousBatcher)
    b._lock = threading.Lock()
    b.prefill_token_budget = 64
    b.set_prefill_token_budget(8)
    assert b.prefill_token_budget == 8
    with pytest.raises(ValueError, match=">= 1"):
        b.set_prefill_token_budget(0)
    assert b.prefill_token_budget == 8  # reject leaves the knob untouched


def test_spec_controller_k_max_zero_silences_probe():
    from photon_tpu.serve.draft import SpecController

    sc = SpecController(4, probe_ticks=2)
    with pytest.raises(ValueError, match=">= 0"):
        sc.set_k_max(-1)
    sc.set_k_max(0)
    assert sc.k_effective() == 0
    # the periodic probe is clamped to the ceiling: fully off stays off
    assert [sc.next_k() for _ in range(5)] == [0, 0, 0, 0, 0]
    sc.set_k_max(2)
    assert sc.k_effective() == 2  # optimistic EWMA re-engages immediately


def test_collective_setters_loud_rejects():
    from photon_tpu.federation.collective_round import CollectiveFedRunner

    r = object.__new__(CollectiveFedRunner)
    r.stage_timeout_s = 30.0
    r.set_stage_timeout_s(10.0)
    assert r.stage_timeout_s == 10.0
    for bad in (0.0, -1.0, float("nan"), float("inf")):
        with pytest.raises(ValueError):
            r.set_stage_timeout_s(bad)
    assert r.stage_timeout_s == 10.0
    r.quantization = "off"
    r.device_plane = None
    r.set_quantization("q8")
    assert r.quantization == "q8"
    r.set_quantization("q8")  # idempotent no-op
    with pytest.raises(ValueError, match="unknown collective quantization"):
        r.set_quantization("int4")


def test_async_staleness_setter_rejects_negative():
    from photon_tpu.federation.async_round import AsyncFedRunner

    r = object.__new__(AsyncFedRunner)
    r.max_staleness = 4
    r.set_max_staleness(0)  # 0 is legal: only same-version deltas fold
    assert r.max_staleness == 0
    with pytest.raises(ValueError, match=">= 0"):
        r.set_max_staleness(-1)


def test_adapter_pool_shrink_skips_pinned_pages():
    from photon_tpu.adapters.lora import AdapterSpec
    from photon_tpu.serve.adapter_pool import AdapterPool

    spec = AdapterSpec(
        rank=2, alpha=4.0,
        entries=(("blocks/block/up_proj", (4, 2), (2, 4)),),
    )
    pool = AdapterPool(spec, pool_size=2)
    pool.install_bank({
        c: [np.zeros((4, 2), np.float32), np.zeros((2, 4), np.float32)]
        for c in ("a", "b")
    })
    pa = pool.acquire("a")          # pinned by a live slot
    pool.release(pool.acquire("b"))  # resident, unpinned
    assert pool.shrink() == 1        # only b is evictable
    assert pool.has_cohort("a") and pool.can_acquire("a")
    assert "b" not in pool._pages and "a" in pool._pages
    pool.release(pa)                 # the pin is still valid after shrink
    assert pool.shrink() == 1        # a unpins → evictable now


# ---------------------------------------------------------------------------
# 3. the controller, on an injected clock
# ---------------------------------------------------------------------------


def test_breach_actuates_once_per_cooldown_then_saturates_once():
    clk = _Clock(100.0)
    ap = _install(clk, period_s=0.25, cooldown_s=2.0,
                  queue_high_frac=0.75, queue_clear_frac=0.25,
                  prefill_budget_min=16, prefill_shrink=0.5)
    state = {"budget": 64}
    ap.register_knob(AUTOPILOT_KNOB_PREFILL_BUDGET,
                     lambda: state["budget"],
                     lambda v: state.__setitem__("budget", v), integer=True)
    hub = telemetry.metrics_active()
    hub.gauge(SERVE_QUEUE_DEPTH).set(100.0)  # frac 1.0 ≥ 0.75: breach
    telemetry.drain_events()

    ap.tick("serve", max_queue=100)
    assert state["budget"] == 32
    kinds = _event_kinds()
    assert kinds.count(EVENT_AUTOPILOT_ACTUATION) == 1
    d = ap.decisions[-1]
    assert d["rule"] == "queue_budget"
    assert d["knob"] == AUTOPILOT_KNOB_PREFILL_BUDGET
    assert (d["old"], d["new"]) == (64, 32)
    assert d["observed"] == 1.0

    clk.advance(0.3)  # period elapsed, cooldown NOT
    ap.tick("serve", max_queue=100)
    assert state["budget"] == 32

    clk.advance(2.0)  # cooldown elapsed: one more shrink, to the bound
    ap.tick("serve", max_queue=100)
    assert state["budget"] == 16

    clk.advance(2.1)  # at the bound: saturated, exactly once per episode
    ap.tick("serve", max_queue=100)
    clk.advance(2.1)
    ap.tick("serve", max_queue=100)
    assert state["budget"] == 16
    sat = [d for d in ap.decisions
           if d["event"] == EVENT_AUTOPILOT_SATURATED]
    assert len(sat) == 1
    assert ap.statusz()["rules"]["queue_budget"]["saturated"] is True


def test_hysteresis_relax_probes_back_toward_declared():
    clk = _Clock(0.0)
    ap = _install(clk, period_s=0.25, cooldown_s=0.5, relax_after=3,
                  window_s=30.0, queue_high_frac=0.75,
                  queue_clear_frac=0.25, prefill_budget_min=16,
                  prefill_shrink=0.5)
    state = {"budget": 64}
    ap.register_knob(AUTOPILOT_KNOB_PREFILL_BUDGET,
                     lambda: state["budget"],
                     lambda v: state.__setitem__("budget", v), integer=True)
    hub = telemetry.metrics_active()
    hub.gauge(SERVE_QUEUE_DEPTH).set(100.0)
    ap.tick("serve", max_queue=100)
    assert state["budget"] == 32

    # age the saturated samples out of the window, then run clean evals
    clk.advance(31.0)
    for i in range(3):
        hub.gauge(SERVE_QUEUE_DEPTH).set(5.0)  # frac 0.05 ≤ 0.25: clean
        ap.tick("serve", max_queue=100)
        clk.advance(0.3)
    # third consecutive clean eval relaxes one integer step toward 64
    assert state["budget"] == 33
    relax = [d for d in ap.decisions if d["event"] == EVENT_AUTOPILOT_RELAX]
    assert len(relax) == 1
    assert (relax[0]["old"], relax[0]["new"]) == (32, 33)


def test_dead_band_neither_tightens_nor_earns_relax_credit():
    clk = _Clock(0.0)
    ap = _install(clk, period_s=0.1, cooldown_s=0.0, relax_after=2,
                  window_s=1.0, queue_high_frac=0.75, queue_clear_frac=0.25,
                  prefill_budget_min=16, prefill_shrink=0.5)
    state = {"budget": 64}
    ap.register_knob(AUTOPILOT_KNOB_PREFILL_BUDGET,
                     lambda: state["budget"],
                     lambda v: state.__setitem__("budget", v), integer=True)
    hub = telemetry.metrics_active()
    hub.gauge(SERVE_QUEUE_DEPTH).set(100.0)
    ap.tick("serve", max_queue=100)
    assert state["budget"] == 32
    for _ in range(4):  # frac 0.5 sits between clear 0.25 and breach 0.75
        clk.advance(1.1)
        hub.gauge(SERVE_QUEUE_DEPTH).set(50.0)
        ap.tick("serve", max_queue=100)
    assert state["budget"] == 32  # no tighten, no relax
    assert ap.statusz()["rules"]["queue_budget"]["clean_streak"] == 0


def test_quantization_enum_escalates_then_saturates():
    clk = _Clock(0.0)
    ap = _install(clk, period_s=0.1, cooldown_s=0.0,
                  wire_slope_bytes_per_s=10.0)
    state = {"q": "off"}
    ap.register_knob(AUTOPILOT_KNOB_QUANT_LEVEL,
                     lambda: state["q"],
                     lambda v: state.__setitem__("q", v),
                     levels=("off", "q8"))
    hub = telemetry.metrics_active()
    c = hub.counter(COLLECTIVE_WIRE_BYTES)
    c.inc(1.0)
    clk.advance(1.0)
    c.inc(100.0)  # slope ≈ 100 B/s > 10
    ap.tick("collective")
    assert state["q"] == "q8"
    d = [d for d in ap.decisions
         if d["event"] == EVENT_AUTOPILOT_ACTUATION][-1]
    assert (d["old"], d["new"]) == ("off", "q8")
    clk.advance(0.2)
    c.inc(100.0)
    ap.tick("collective")  # still breaching at the ladder's top
    assert state["q"] == "q8"
    assert any(d["event"] == EVENT_AUTOPILOT_SATURATED
               for d in ap.decisions)


def test_async_reject_rate_widens_staleness_bound():
    clk = _Clock(0.0)
    ap = _install(clk, period_s=0.1, cooldown_s=0.0,
                  async_reject_per_version=0.5, max_staleness_hi=8)
    state = {"s": 2}
    ap.register_knob(AUTOPILOT_KNOB_MAX_STALENESS,
                     lambda: state["s"],
                     lambda v: state.__setitem__("s", v), integer=True)
    ap.tick("async", rejected_total=0, version=1)  # primes the delta
    assert state["s"] == 2
    clk.advance(0.2)
    ap.tick("async", rejected_total=3, version=2)  # 3 rejects/version
    assert state["s"] == 3
    # bounds: declared is the floor, max_staleness_hi the ceiling
    z = ap.statusz()["knobs"][AUTOPILOT_KNOB_MAX_STALENESS]
    assert (z["lo"], z["hi"]) == (2.0, 8.0)


def test_hbm_alert_latch_fires_reclaim_once_per_alert():
    clk = _Clock(50.0)
    ap = _install(clk, period_s=0.1, cooldown_s=0.0)
    calls = []
    ap.register_action(AUTOPILOT_ACTION_RECLAIM,
                       lambda: (calls.append(1), (10.0, 26.0))[1])
    health = telemetry.health_active()
    health.alert(ALERT_HBM_GROWTH, plane="serve", growth_frac=0.4)
    ap.tick("serve", max_queue=8)
    assert calls == [1]
    d = ap.decisions[-1]
    assert d["rule"] == "hbm_reclaim"
    assert d["knob"] == AUTOPILOT_ACTION_RECLAIM
    assert (d["old"], d["new"]) == (10.0, 26.0)
    assert d["observed"] == 0.4
    clk.advance(0.2)
    ap.tick("serve", max_queue=8)  # same alert: no second reclaim
    assert calls == [1]
    clk.advance(0.2)
    health.alert(ALERT_HBM_GROWTH, plane="serve", growth_frac=0.6)
    ap.tick("serve", max_queue=8)  # a NEW alert fires again
    assert calls == [1, 1]


def test_replica_restart_cooldown_is_per_replica():
    clk = _Clock(0.0)
    ap = _install(clk, cooldown_s=5.0)
    assert ap.request_replica_restart("r0", "compile_growth") is True
    assert ap.request_replica_restart("r0", "compile_growth") is False
    assert ap.request_replica_restart("r1", ALERT_HBM_GROWTH) is True
    clk.advance(6.0)
    assert ap.request_replica_restart("r0", "compile_growth") is True
    d = ap.decisions[-1]
    assert d["knob"] == AUTOPILOT_ACTION_RESTART
    assert (d["old"], d["new"]) == ("live", "restarting")
    assert d["replica"] == "r0"


def test_tick_never_raises_out_of_the_hook_site():
    clk = _Clock(0.0)
    ap = _install(clk, period_s=0.1, cooldown_s=0.0)

    def _bad_setter(v):
        raise RuntimeError("actuator wired wrong")

    ap.register_knob(AUTOPILOT_KNOB_PREFILL_BUDGET,
                     lambda: 64, _bad_setter, integer=True)
    hub = telemetry.metrics_active()
    hub.gauge(SERVE_QUEUE_DEPTH).set(100.0)
    with pytest.warns(UserWarning, match="autopilot tick failed"):
        ap.tick("serve", max_queue=100)


def test_install_path_and_disabled_is_one_none_check():
    tel = TelemetryConfig(enabled=True)
    telemetry.install(tel, scope="t")
    assert telemetry.autopilot_active() is None  # autopilot default off
    tel.autopilot.enabled = True
    telemetry.install(tel, scope="t")
    ap = telemetry.autopilot_active()
    assert ap is not None
    assert {r.name for r in ap._rules} == {"queue_budget", "hbm_reclaim"}
    telemetry.uninstall()
    assert telemetry.autopilot_active() is None


def test_config_validation_rejects_bad_autopilot_blocks():
    cfg = Config()
    cfg.photon.telemetry.enabled = True
    cfg.photon.telemetry.autopilot.enabled = True
    cfg.validate()  # defaults are legal
    cfg.photon.telemetry.autopilot.period_s = 0.0
    with pytest.raises(ValueError):
        cfg.validate()
    cfg.photon.telemetry.autopilot.period_s = 0.25
    cfg.photon.telemetry.autopilot.queue_clear_frac = 0.9  # ≥ high_frac
    with pytest.raises(ValueError):
        cfg.validate()
    cfg.photon.telemetry.autopilot.queue_clear_frac = 0.25
    cfg.photon.telemetry.enabled = False  # autopilot needs the plane
    with pytest.raises(ValueError):
        cfg.validate()


def test_statusz_surfaces_decisions_rules_and_knob_bounds():
    clk = _Clock(0.0)
    ap = _install(clk, prefill_budget_min=16)
    state = {"budget": 64}
    ap.register_knob(AUTOPILOT_KNOB_PREFILL_BUDGET,
                     lambda: state["budget"],
                     lambda v: state.__setitem__("budget", v), integer=True)
    z = ap.statusz()
    assert set(z) == {"decisions", "rules", "knobs"}
    k = z["knobs"][AUTOPILOT_KNOB_PREFILL_BUDGET]
    assert (k["value"], k["declared"], k["lo"], k["hi"]) == (64, 64, 16.0, 64.0)
    assert set(z["rules"]) == {"queue_budget", "hbm_reclaim"}


def test_prom_statusz_merges_autopilot_payload():
    import json
    import urllib.request

    from photon_tpu.metrics.history import History
    from photon_tpu.telemetry.prom import PromServer

    clk = _Clock(0.0)
    ap = _install(clk)
    srv = PromServer(History(), port=0,
                     hub=telemetry.metrics_active(),
                     health=telemetry.health_active())
    srv.start()
    try:
        z = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/statusz", timeout=5
        ).read())
        assert set(z["autopilot"]) == {"decisions", "rules", "knobs"}
        assert "queue_budget" in z["autopilot"]["rules"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# 4. seeded chaos storm through the real scheduler (slow: engine compile)
# ---------------------------------------------------------------------------


def _storm_cfg() -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 4
    cfg.model.vocab_size = 96
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.max_seq_len = 64
    cfg.photon.serve.n_slots = 2
    cfg.photon.serve.block_size = 4
    cfg.photon.serve.max_new_tokens = 4
    cfg.photon.telemetry.enabled = True
    apc = cfg.photon.telemetry.autopilot
    apc.enabled = True
    apc.period_s = 0.05
    apc.cooldown_s = 0.1
    apc.queue_high_frac = 0.3
    apc.queue_clear_frac = 0.1
    apc.prefill_budget_min = 4
    apc.prefill_shrink = 0.5
    cfg.photon.chaos = ChaosConfig(
        enabled=True, seed=1234, serve_stall_per_token_s=0.002,
    )
    return cfg.validate()


@pytest.mark.slow
@pytest.mark.chaos
def test_storm_autopilot_shrinks_budget_and_surfaces_decisions():
    import time

    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    from photon_tpu.serve.frontend import ServeFrontend

    cfg = _storm_cfg()
    telemetry.install(cfg.photon.telemetry, scope="serve")
    chaos.install(cfg.photon.chaos, scope="serve")
    engine = PagedEngine(cfg, init_params(cfg.model, seed=4))
    batcher = ContinuousBatcher(
        engine, max_queue=8, prefill_token_budget=32,
    ).start()
    fe = ServeFrontend(batcher)
    fe_port = fe.start()
    ap = telemetry.autopilot_active()
    assert ap is not None
    z = ap.statusz()["knobs"][AUTOPILOT_KNOB_PREFILL_BUDGET]
    assert z["declared"] == 32
    try:
        rng = np.random.default_rng(0)
        handles = []
        # fat prompts + per-token chaos stall: the queue EWMA saturates
        # against queue_high_frac and the controller must shrink the
        # budget (6 < max_queue=8 so admission itself never rejects)
        for _ in range(6):
            prompt = [int(x) for x in rng.integers(1, 96, 24)]
            handles.append(batcher.submit(prompt, max_new_tokens=2))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            done = all(h.finished for h in handles)
            if done and batcher.prefill_token_budget < 32:
                break
            time.sleep(0.05)
        assert batcher.rejected == 0  # the queue never overflowed
        assert batcher.prefill_token_budget < 32
        decisions = ap.statusz()["decisions"]
        acts = [d for d in decisions
                if d["event"] == EVENT_AUTOPILOT_ACTUATION
                and d["knob"] == AUTOPILOT_KNOB_PREFILL_BUDGET]
        assert acts, f"no budget actuation in {decisions}"
        assert acts[0]["rule"] == "queue_budget"
        assert acts[0]["old"] == 32
        # chaos accounted its own storm
        inj = chaos.active()
        assert inj.counts["serve_stall"] > 0
        # the decisions surface on the serve frontend's /statusz too
        import json
        import urllib.request

        z = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{fe_port}/statusz", timeout=30).read())
        assert any(d["event"] == EVENT_AUTOPILOT_ACTUATION
                   for d in z["autopilot"]["decisions"])
        assert z["autopilot"]["knobs"][AUTOPILOT_KNOB_PREFILL_BUDGET][
            "value"] < 32
    finally:
        fe.close()
        batcher.close()
