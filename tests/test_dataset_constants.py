"""mC4 constants registry + stream-remap knob (VERDICT r3 missing #2/#5)."""

import pytest

from photon_tpu.data.constants import (
    DATASETS_CONSTANTS,
    MC4_LANGUAGES,
    resolve_split,
)


def test_registry_covers_reference_languages():
    # photon/dataset/constants/mc4.py pins exactly these 13 languages
    assert set(MC4_LANGUAGES) == {
        "en", "sr", "la", "sw", "ur", "ms", "zh", "it", "es", "de", "el", "ru", "hi"
    }
    assert set(DATASETS_CONSTANTS) == {f"c4_{l}" for l in MC4_LANGUAGES}


def test_english_truncated_splits_match_reference():
    en = DATASETS_CONSTANTS["c4_en"]
    assert en.splits["train_small"].truncated_samples == 100_000
    assert en.splits["val_small"].truncated_samples == 10_000
    assert en.splits["val_xsmall"].truncated_samples == 3_000
    assert en.splits["val_xxsmall"].truncated_samples == 100
    assert en.splits["train"].truncated_samples is None
    # folder_split maps HF "validation" -> local "val" dirs
    assert en.splits["validation"].folder_split == "val"


def test_non_english_languages_have_full_splits_only():
    for lang in MC4_LANGUAGES:
        if lang == "en":
            continue
        consts = DATASETS_CONSTANTS[f"c4_{lang}"]
        assert set(consts.splits) == {"train", "validation"}
        for sp in consts:
            assert sp.truncated_samples is None
            assert sp.name == lang
            assert sp.path == "allenai/c4"


def test_resolve_split_errors_are_actionable():
    with pytest.raises(KeyError, match="unknown dataset key"):
        resolve_split("c4_xx", "train")
    with pytest.raises(KeyError, match="no split"):
        resolve_split("c4_sr", "train_small")


def test_stream_remap_modulo(tmp_path):
    """n_streams=2 maps cid 5 onto client_1's stream (streams[cid % n],
    reference llm_config_functions.py:388-436)."""
    from photon_tpu.config.schema import Config
    from photon_tpu.data import make_synthetic_dataset
    from photon_tpu.federation.client_runtime import ClientRuntime
    from photon_tpu.federation.transport import ParamTransport

    for i in range(2):
        make_synthetic_dataset(
            str(tmp_path / f"client_{i}" / "train"),
            n_samples=8, seq_len=16, vocab_size=64, seed=i,
        )
    cfg = Config()
    cfg.model.d_model = 16
    cfg.model.n_layers = 1
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 4
    cfg.train.device_microbatch_size = 4
    cfg.dataset.local_path = str(tmp_path)
    cfg.dataset.synthetic = False
    cfg.dataset.n_streams = 2
    cfg.photon.save_path = str(tmp_path / "run")
    cfg.validate()

    rt = ClientRuntime(cfg, ParamTransport("inline"))
    loader_5 = rt._loader(5, "train", batch_size=4)   # 5 % 2 == 1
    loader_1 = rt._loader(1, "train", batch_size=4)
    b5, b1 = next(iter(loader_5)), next(iter(loader_1))
    assert b5.shape == b1.shape == (4, 16)
    # same underlying stream: both loaders read client_1's dataset
    ds5 = rt._loaders[(5, "train")].ds
    ds1 = rt._loaders[(1, "train")].ds
    assert ds5.path == ds1.path
    assert ds5.path.parts[-2] == "client_1"
