"""Centralized trainer entry: smoke run on synthetic data + resume."""

import pytest
import numpy as np

from photon_tpu.config.schema import (
    Config, MeshConfig, ModelConfig, OptimizerConfig, PhotonConfig, SchedulerConfig, TrainConfig,
)
from photon_tpu.centralized import run_centralized
from photon_tpu.data import ShardWriter, ShardedDataset
from photon_tpu.data.loader import ConcatDataset, StreamingLoader


def _cfg(tmp_path) -> Config:
    cfg = Config(
        run_uuid="central-test",
        model=ModelConfig(
            d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
            attn_impl="xla", compute_dtype="float32",
        ),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=20),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4, eval_batches=2, log_interval=2),
        photon=PhotonConfig(save_path=str(tmp_path / "save"), checkpoint=True, keep_checkpoints=2),
    )
    cfg.dataset.synthetic = True
    return cfg.validate()


@pytest.mark.slow
def test_centralized_smoke_and_resume(tmp_path, capsys):
    cfg = _cfg(tmp_path)
    h1 = run_centralized(cfg, total_steps=4, eval_first=True, dump_params=True)
    assert h1.latest("eval/loss") is not None
    assert (tmp_path / "save" / "params_init.npz").exists()
    assert (tmp_path / "save" / "params_final.npz").exists()

    # resume continues from the checkpoint instead of restarting
    h2 = run_centralized(cfg, total_steps=6)
    steps = [s for s, _ in h2.series("client/steps")]
    assert steps and max(steps) == 6


def test_concat_dataset_roundtrip(tmp_path):
    for part, base in ((0, 0), (1, 100)):
        with ShardWriter(tmp_path / f"p{part}", 8, 256, samples_per_shard=4) as w:
            for i in range(10):
                w.write(np.full(8, base + i, np.int64))
    ds = ConcatDataset([ShardedDataset(tmp_path / "p0"), ShardedDataset(tmp_path / "p1")])
    assert len(ds) == 20
    assert (ds[0] == 0).all() and (ds[10] == 100).all() and (ds[19] == 109).all()
    # loader over the concat sees every sample exactly once per epoch
    loader = StreamingLoader(ds, batch_size=5, seed=0)
    seen = []
    for _ in range(4):
        seen.extend(int(v) for v in next(loader)[:, 0])
    assert sorted(seen) == sorted(list(range(10)) + list(range(100, 110)))
