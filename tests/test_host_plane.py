"""Host-plane round pipeline (ISSUE 2): shared pool semantics, bit-exact
fused/parallel aggregation, decode-ahead, KPI metrics, async checkpoints.

The load-bearing contract: every pipeline mode (serial, threads=1 inline,
threads=N) applies identical per-element operations in identical order, so
the aggregated fp32 result is BYTE-identical across configurations — the
``photon.host_threads`` knob moves wall-clock only, never results.
"""

import time

import numpy as np
import pytest

from photon_tpu.checkpoint import FileStore, ServerCheckpointManager
from photon_tpu.codec import ParamsMetadata
from photon_tpu.compression import Codec
from photon_tpu.strategy.aggregation import _FOLD_CHUNK, _fold_into, aggregate_inplace
from photon_tpu.utils.hostpool import HostPool, resolve_host_threads
from photon_tpu.utils.profiling import (
    AGG_DECODE_TIME,
    AGG_FOLD_TIME,
    CKPT_ASYNC_WRITE_S,
)


# ---------------------------------------------------------------------------
# HostPool
# ---------------------------------------------------------------------------


def test_hostpool_inline_degenerate():
    pool = HostPool(1)
    assert not pool.pipelined
    assert pool.submit(lambda a, b: a + b, 2, 3).result() == 5
    assert pool.map(lambda x: x * x, [1, 2, 3]) == [1, 4, 9]
    # inline futures surface exceptions at result(), like real ones
    fut = pool.submit(lambda: 1 / 0)
    with pytest.raises(ZeroDivisionError):
        fut.result()
    pool.close()  # no executor was ever created; must be a no-op


def test_hostpool_threaded_ordered_and_reusable():
    pool = HostPool(3)
    assert pool.pipelined
    assert pool.map(lambda x: x * 2, list(range(20))) == [x * 2 for x in range(20)]
    pool.close()
    # close() is reusable: the next submit rebuilds the executor
    assert pool.submit(lambda: 7).result() == 7
    pool.close()


def test_resolve_host_threads():
    assert resolve_host_threads(4) == 4
    assert resolve_host_threads(1) == 1
    auto = resolve_host_threads(0)
    assert 1 <= auto <= 8  # bounded; leaves a core for the driving thread


# ---------------------------------------------------------------------------
# Fused fold: bit-exact + no full-payload fp64 temporary
# ---------------------------------------------------------------------------


def _payload(seed, n_layers=7):
    rng = np.random.default_rng(seed)
    shapes = [(129, 65), (513,), (33, 9, 5), (2048,), (7, 7), (1,), (300, 11)][:n_layers]
    return [rng.normal(size=s).astype(np.float32) for s in shapes]


def _stream(n_clients=6, seed=0):
    rng = np.random.default_rng(seed)
    return [(_payload(seed + i), int(n)) for i, n in enumerate(rng.integers(1, 200, n_clients))]


def test_fused_fold_matches_two_pass_bitwise():
    clients = _stream()
    acc_ref = [np.asarray(a, np.float64) for a in clients[0][0]]
    n_total = clients[0][1]
    for arrays, n_cur in clients[1:]:
        n_new = n_total + n_cur
        w_prev, w_cur = n_total / n_new, n_cur / n_new
        for i, y in enumerate(arrays):
            # the pre-PR-2 two-pass fold, full fp64 temp and all
            acc_ref[i] *= w_prev
            acc_ref[i] += np.asarray(y, np.float64) * w_cur
        n_total = n_new
    expect = [a.astype(np.float32) for a in acc_ref]

    got, n = aggregate_inplace(iter(clients))
    assert n == n_total
    for a, b in zip(expect, got):
        assert a.dtype == b.dtype and np.array_equal(a, b)


@pytest.mark.parametrize("threads", [1, 4])
def test_aggregate_parity_raw_threads(threads):
    clients = _stream()
    serial, n1 = aggregate_inplace(iter(clients))
    timings: dict = {}
    pooled, n2 = aggregate_inplace(iter(clients), pool=HostPool(threads), timings=timings)
    assert n1 == n2
    for a, b in zip(serial, pooled):
        assert np.array_equal(a, b), "threaded fold is not bit-exact"
    assert timings["decode_s"] >= 0.0 and timings["fold_s"] > 0.0


@pytest.mark.parametrize("threads", [1, 4])
def test_aggregate_parity_compressed_threads(threads):
    clients = _stream()
    names = [f"l{i}/w" for i in range(len(clients[0][0]))]
    meta = ParamsMetadata.from_ndarrays(names, clients[0][0])
    ref = [a + 0.01 for a in clients[0][0]]

    enc = Codec("delta_topk_q8", error_feedback=False)
    enc.set_reference(ref)
    payloads = [(enc.encode(meta, arrays), n) for arrays, n in clients]

    dec = Codec("delta_topk_q8", error_feedback=False)
    dec.set_reference(ref)
    serial, _ = aggregate_inplace(iter(payloads), decode=dec.decode)
    pool = HostPool(threads)
    pooled, _ = aggregate_inplace(
        iter(payloads), decode=lambda p: dec.decode(p, pool=pool), pool=pool
    )
    for a, b in zip(serial, pooled):
        assert np.array_equal(a, b), "pipelined compressed fold is not bit-exact"


def test_codec_pool_encode_decode_identical_bytes():
    arrays = _payload(3)
    names = [f"l{i}/w" for i in range(len(arrays))]
    meta = ParamsMetadata.from_ndarrays(names, arrays)
    ref = [a + 0.01 for a in arrays]
    pool = HostPool(4)
    for policy in ("delta_q8", "delta_topk_q8"):
        codec = Codec(policy, error_feedback=True)
        codec.set_reference(ref)
        serial_bytes = codec.encode(meta, arrays, key=1).to_bytes()
        codec2 = Codec(policy, error_feedback=True)
        codec2.set_reference(ref)
        pooled_bytes = codec2.encode(meta, arrays, key=1, pool=pool).to_bytes()
        assert serial_bytes == pooled_bytes, policy
        # decode parity, pooled vs serial
        from photon_tpu.compression import CompressedPayload

        payload = CompressedPayload.from_bytes(pooled_bytes)
        for a, b in zip(codec.decode(payload), codec.decode(payload, pool=pool)):
            assert np.array_equal(a, b)


def test_fused_fold_peak_allocation_is_chunk_not_payload():
    """The acceptance bound: no full-payload ``astype(np.float64)`` temp.

    A 16 MiB fp32 incoming array would have cost a 32 MiB fp64 temporary in
    the old two-pass fold; the fused chunked fold's transient must stay at
    chunk scale (~8 MiB)."""
    import tracemalloc

    n = 4 << 20  # 16 MiB fp32 / 32 MiB fp64
    y = np.full(n, 0.5, np.float32)
    acc = np.ones(n, np.float64)
    tracemalloc.start()
    try:
        tracemalloc.reset_peak()
        _fold_into(acc, y, 0.25, 0.75)
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    chunk_bytes = _FOLD_CHUNK * 8
    assert peak < 2 * chunk_bytes, (
        f"fold transient {peak / 2**20:.1f} MiB — a full fp64 payload copy "
        f"({y.size * 8 / 2**20:.0f} MiB) appears to be materialized again"
    )
    # and the math still holds
    np.testing.assert_allclose(acc, 0.25 + 0.5 * 0.75)


def test_aggregate_first_client_non_contiguous_fp64():
    """Regression (review): an already-fp64 NON-contiguous first payload
    used to flow through ``asarray`` unchanged, making ``reshape(-1)`` in
    the fold a copy — every later client's contribution silently dropped."""
    base = np.arange(16, dtype=np.float64).reshape(4, 4)
    nc = base.T
    assert not nc.flags.c_contiguous
    rest = np.full((4, 4), 2.0, np.float32)
    avg, n = aggregate_inplace(iter([([nc], 1), ([rest], 3)]))
    assert n == 4
    expect = (nc * 0.25 + rest.astype(np.float64) * 0.75).astype(np.float32)
    np.testing.assert_array_equal(avg[0], expect)
    # the fold primitive itself refuses a non-contiguous accumulator
    with pytest.raises(ValueError, match="contiguous"):
        _fold_into(base.T, rest, 0.5, 0.5)


def test_agg_decode_time_excludes_blocking_fetch():
    """Regression (review): the decode KPI must not absorb the wait for a
    client's reply — in production ``next(it)`` blocks on the driver for
    the whole client fit."""
    def slow_stream():
        yield _payload(0), 2
        time.sleep(0.25)  # "client still training"
        yield _payload(1), 3

    timings: dict = {}
    aggregate_inplace(slow_stream(), timings=timings)
    assert timings["decode_s"] < 0.2, (
        f"decode_s={timings['decode_s']:.3f}s charged the client wait"
    )


def test_aggregate_error_propagates_from_lookahead():
    def boom():
        yield _payload(0), 3
        yield _payload(1), 2
        raise RuntimeError("stream died")

    with pytest.raises(RuntimeError, match="stream died"):
        aggregate_inplace(boom(), pool=HostPool(4))
    with pytest.raises(ValueError, match="non-positive"):
        aggregate_inplace(iter([(_payload(0), 5), (_payload(1), 0)]), pool=HostPool(4))


# ---------------------------------------------------------------------------
# Async server checkpoints
# ---------------------------------------------------------------------------


class SlowStore(FileStore):
    """FileStore with a per-put delay + completion timestamps."""

    def __init__(self, root, delay=0.15):
        super().__init__(root)
        self.delay = delay
        self.completed: dict[str, float] = {}

    def put(self, key, data):
        time.sleep(self.delay)
        super().put(key, data)
        self.completed[key] = time.monotonic()


def _round_payload(seed=0):
    meta_arrays = _payload(seed, n_layers=3)
    names = [f"l{i}/w" for i in range(len(meta_arrays))]
    return ParamsMetadata.from_ndarrays(names, meta_arrays), meta_arrays


def test_async_save_then_load_barrier(tmp_path):
    """load/resume must never observe a half-landed async round."""
    store = SlowStore(tmp_path, delay=0.1)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _round_payload()
    t0 = time.monotonic()
    enqueue_s = mgr.save_round_async(
        5, meta, params, {"momentum": params}, {"round": 5},
        cleanup_keep=(3, ("momentum",)),
    )
    assert enqueue_s < 0.05  # snapshot+enqueue is cheap; the writes are not
    assert time.monotonic() - t0 < 0.1  # did not block on the slow puts
    assert mgr.last_barrier_wait_s < 0.05  # no previous write to wait out
    # immediate read: the internal barrier waits the writer out
    m, p, st, server_state = mgr.load_round(5, ("momentum",))
    assert server_state == {"round": 5}
    np.testing.assert_array_equal(p[0], params[0])
    assert mgr.resolve_resume_round(-1, ("momentum",)) == 5
    assert mgr.last_async_write_s > 0.0


def test_async_save_write_error_surfaces_at_barrier(tmp_path):
    class BrokenStore(FileStore):
        def put(self, key, data):
            raise OSError("disk on fire")

    mgr = ServerCheckpointManager(BrokenStore(tmp_path), "run1")
    meta, params = _round_payload()
    mgr.save_round_async(1, meta, params)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait_pending()
    # the error is consumed — the manager is usable again
    mgr.wait_pending()


def test_async_snapshot_isolated_from_later_mutation(tmp_path):
    """The snapshot contract is ONE-level: list/dict containers are copied,
    slots may be rebound afterwards (that is all the strategies and the
    server do — ServerApp additionally one-level-copies ``client_states``
    at build time because IT keeps inserting into that nested dict)."""
    store = SlowStore(tmp_path, delay=0.05)
    mgr = ServerCheckpointManager(store, "run1")
    meta, params = _round_payload()
    momenta = [np.zeros_like(a) for a in params]
    server_state = {"client_states": {0: {"steps": 1}}, "round": 1}
    mgr.save_round_async(1, meta, params, {"momentum": momenta}, server_state)
    # what the round loop does next, while the writer is still asleep:
    momenta[0] = np.full_like(momenta[0], 9.0)        # slot REBIND (not in-place)
    server_state["client_states"] = {9: {"steps": 9}}  # key REBIND
    server_state["round"] = 2
    _, _, st, loaded = mgr.load_round(1, ("momentum",))
    np.testing.assert_array_equal(st["momentum"][0], np.zeros_like(params[0]))
    assert loaded == {"client_states": {0: {"steps": 1}}, "round": 1}


# ---------------------------------------------------------------------------
# Federated rounds: KPI keys, degenerate threads=1, write/round overlap
# ---------------------------------------------------------------------------


def _fed_app(tmp_path, store=None, host_threads=1, n_rounds=2, checkpoint=False,
             **fl_kw):
    from photon_tpu.federation import InProcessDriver, NodeAgent, ParamTransport, ServerApp
    from tests.test_federation import make_cfg

    cfg = make_cfg(tmp_path, n_rounds=n_rounds, **fl_kw)
    cfg.photon.host_threads = host_threads
    cfg.photon.checkpoint = checkpoint
    cfg.validate()
    transport = ParamTransport("inline")

    def make_agent(node_id):
        return NodeAgent(cfg, node_id, lambda: ParamTransport("inline"))

    driver = InProcessDriver(cfg, make_agent, n_nodes=2)
    ckpt = ServerCheckpointManager(store, cfg.run_uuid) if store is not None else None
    return ServerApp(cfg, driver, transport, ckpt_mgr=ckpt)


def test_fed_round_host_plane_kpis_and_degenerate_pool(tmp_path):
    """tier-1 coverage for ``photon.host_threads=1`` (the degenerate inline
    pool) + presence of the new host-plane KPI keys in round metrics."""
    store = FileStore(tmp_path / "ckpt")
    app = _fed_app(tmp_path, store=store, host_threads=1, checkpoint=True)
    assert not app.host_pool.pipelined
    history = app.run()
    for key in (AGG_DECODE_TIME, AGG_FOLD_TIME, "server/checkpoint_time",
                CKPT_ASYNC_WRITE_S):
        assert len(history.series(key)) == 2, key
    # the shutdown barrier landed every round on disk
    assert app.ckpt_mgr.valid_rounds(app.strategy.state_keys) != []
    app.driver.shutdown()


def test_fed_round_threaded_pool_matches_serial_params(tmp_path):
    """Same run, host_threads=1 vs 4: byte-identical final parameters (the
    whole-pipeline version of the bit-exact aggregation contract)."""
    app1 = _fed_app(tmp_path / "a", host_threads=1)
    app1.run()
    p1 = [a.copy() for a in app1.strategy.current_parameters]
    app1.driver.shutdown()

    app4 = _fed_app(tmp_path / "b", host_threads=4)
    assert app4.host_pool.pipelined
    app4.run()
    p4 = app4.strategy.current_parameters
    app4.driver.shutdown()
    for a, b in zip(p1, p4):
        assert np.array_equal(a, b), "host_threads changed the aggregation result"


def test_async_checkpoint_overlaps_next_round(tmp_path):
    """Round N+1's broadcast must fire BEFORE round N's checkpoint write
    completes (the write overlaps the next round), and the run's shutdown
    barrier still leaves every round consistent on disk."""
    store = SlowStore(tmp_path / "ckpt", delay=0.15)
    app = _fed_app(tmp_path, store=store, host_threads=1, n_rounds=2, checkpoint=True)

    bcast_at: dict[int, float] = {}
    orig = app.broadcast_parameters

    def timed_broadcast(server_round):
        bcast_at.setdefault(server_round, time.monotonic())
        return orig(server_round)

    app.broadcast_parameters = timed_broadcast
    app.run()

    done_r1 = store.completed[f"{app.cfg.run_uuid}/server/1/state.bin"]
    assert bcast_at[2] < done_r1, (
        f"round-2 broadcast at {bcast_at[2]:.3f} did not overlap the "
        f"round-1 write completing at {done_r1:.3f}"
    )
    # barrier: after run() both rounds are fully valid and resumable
    mgr = ServerCheckpointManager(store, app.cfg.run_uuid)
    assert 2 in mgr.valid_rounds(app.strategy.state_keys)
    _, p, _, server_state = mgr.load_round(2, app.strategy.state_keys)
    for a, b in zip(p, app.strategy.current_parameters):
        np.testing.assert_array_equal(a, b)
    assert server_state["server_steps_cumulative"] == app.server_steps_cumulative
    app.driver.shutdown()


def test_resume_after_async_checkpoint_matches_uninterrupted(tmp_path):
    """Crash-resume consistency: resume from the latest async-written round
    reproduces the uninterrupted run (PRNG fast-forward + params).
    ``reset_optimizer`` keeps client optimizer state round-local, as in the
    golden determinism oracle in test_federation."""
    fit_cfg = {"fit_config": {"reset_optimizer": True}}
    store = FileStore(tmp_path / "ckpt")
    full = _fed_app(tmp_path / "full", store=store, host_threads=1, n_rounds=3,
                    checkpoint=True, **fit_cfg)
    full.run()
    p_full = [a.copy() for a in full.strategy.current_parameters]
    full.driver.shutdown()

    # fresh app resuming from round 2 of the same store, same run_uuid
    resumed = _fed_app(tmp_path / "full", store=store, host_threads=1, n_rounds=3,
                       checkpoint=True, **fit_cfg)
    resumed.cfg.photon.resume_round = 2
    resumed.run()
    for a, b in zip(p_full, resumed.strategy.current_parameters):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    resumed.driver.shutdown()


# ---------------------------------------------------------------------------
# bench host_plane section
# ---------------------------------------------------------------------------


def test_bench_host_plane_report_smoke():
    import bench

    report = bench.host_plane_report(budget_bytes=1 << 20, n_clients=3, repeats=1)
    assert report is not None
    assert report["cpu_count"] >= 1 and report["threads"] >= 1
    assert report["raw_bytes_full_model"] > report["payload_bytes_per_client"]
    for kind in ("raw", "compressed"):
        sec = report[kind]
        assert sec["bit_exact"] is True
        assert sec["serial_gb_s"] > 0 and sec["pipelined_gb_s"] > 0
        if report["threads"] == 1:
            # degenerate pool: the pipelined path IS the serial path and the
            # report must say so exactly (never-slower holds by construction)
            assert sec["pipelined_s"] == sec["serial_s"]
