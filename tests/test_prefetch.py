"""Prefetch iterator: ordering, bounded consumption (loader state exactness),
error propagation; trainer integration keeps resume determinism."""

import itertools
import time

import numpy as np
import pytest

from photon_tpu.data import StreamingLoader
from photon_tpu.data.prefetch import PrefetchIterator
from tests.test_data import _write_range_dataset


def test_prefetch_preserves_order():
    src = iter(range(50))
    it = PrefetchIterator(src, depth=4)
    assert list(it) == list(range(50))


def test_prefetch_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = PrefetchIterator(gen())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        for _ in it:
            pass


def test_bounded_prefetch_leaves_loader_state_exact(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=60, seq=8)
    loader = StreamingLoader(ds, batch_size=5, seed=1)
    it = PrefetchIterator(itertools.islice(iter(loader), 4), depth=2)
    got = [next(it) for _ in range(4)]
    time.sleep(0.05)  # give the thread a chance to over-pull (it must not)
    assert loader.state.sample_in_epoch == 20  # exactly 4 × 5 consumed
    # continuing the loader directly yields the 5th batch of a fresh replay
    ref = StreamingLoader(ds, batch_size=5, seed=1)
    for _ in range(4):
        next(ref)
    np.testing.assert_array_equal(next(loader), next(ref))
    del got


def test_close_joins_producer_despite_drain_race():
    """Regression (ISSUE 2 satellite): the old ``close()`` drained once and
    returned — a producer that refilled the queue after that drain blocked
    forever (the post-loop ``put(_DONE)`` had no stop check at all),
    leaking a permanently wedged thread. ``close()`` must now JOIN the
    producer, whatever state it is blocked in."""
    # finite source + depth 1 reproduces the worst case: the producer ends
    # its loop with the queue full and goes on to put(_DONE)
    it = PrefetchIterator(iter(range(3)), depth=1)
    assert next(it) == 0
    it.close()
    assert not it._thread.is_alive(), "producer thread leaked past close()"


def test_close_with_infinite_source_and_consumer_gone():
    def forever():
        i = 0
        while True:
            yield i
            i += 1

    it = PrefetchIterator(forever(), depth=2)
    assert next(it) == 0
    it.close()
    assert not it._thread.is_alive()
    # idempotent
    it.close()


def test_trainer_fit_with_loader_resume_exact(tmp_path, tiny_trainer):
    """fit() consuming from a StreamingLoader must leave its state exactly
    duration_steps × batch ahead (prefetch is bounded)."""
    trainer, _ = tiny_trainer
    ds = _write_range_dataset(tmp_path / "ds", n=64, seq=16, vocab=64)
    loader = StreamingLoader(ds, batch_size=4, seed=2)
    trainer.fit(loader, duration_steps=3)
    assert loader.state.sample_in_epoch == 12
