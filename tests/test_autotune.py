"""Layout auto-tuner (ISSUE 14b): enumeration legality, cost-model
monotonicity, ranking sanity on real presets, the federated DCN term, and
the AOT memory-analysis cross-check on an abstract v5e topology (skipped
where libtpu is unavailable). The rank-vs-MEASURED validation lives in
``bench.py --zero1`` (exit-gated): the cost model's top pick must match
the measured-fastest layout on >= 2 emulated mesh shapes."""

import dataclasses

import numpy as np
import pytest

from photon_tpu.config.schema import MeshConfig, ModelConfig
from photon_tpu.parallel.autotune import (
    HardwareModel,
    autotune_layout,
    autotune_mesh,
    enumerate_layouts,
    estimate_layout,
    model_param_count,
    rank_layouts,
)

TINY = ModelConfig(
    d_model=64, n_layers=2, n_heads=4, max_seq_len=32, vocab_size=256,
    attn_impl="xla", compute_dtype="float32",
)


# ---------------------------------------------------------------------------
# enumeration legality
# ---------------------------------------------------------------------------


def test_enumerate_covers_exact_factorizations():
    layouts = enumerate_layouts(TINY, 8, global_batch_size=8)
    for m in layouts:
        assert m.data * m.fsdp * m.tensor * m.pipe == 8
        assert m.sequence == 1 and m.expert == 1
    # pure data-parallel is always among the legal layouts
    assert any(m.data == 8 for m in layouts)


def test_enumerate_respects_divisibility():
    # tensor must divide n_heads (4) AND d_model: tensor=8 is illegal
    assert not any(
        m.tensor == 8 for m in enumerate_layouts(TINY, 8, 8)
    )
    # pipe must divide n_layers (2): pipe=4 and pipe=8 are illegal
    assert not any(
        m.pipe in (4, 8) for m in enumerate_layouts(TINY, 8, 8)
    )
    # GQA: kv heads constrain tensor too
    gqa = dataclasses.replace(TINY, n_kv_heads=2, rope=True,
                              learned_pos_emb=False)
    assert not any(m.tensor == 4 for m in enumerate_layouts(gqa, 8, 8))
    assert any(m.tensor == 2 for m in enumerate_layouts(gqa, 8, 8))


def test_enumerate_pipeline_single_batch_axis():
    # the schema allows at most ONE batch-sharded axis with pipe > 1
    deep = dataclasses.replace(TINY, n_layers=8)
    for m in enumerate_layouts(deep, 8, 8):
        if m.pipe > 1:
            assert not (m.data > 1 and m.fsdp > 1)


def test_enumerate_max_pipe_cap():
    deep = dataclasses.replace(TINY, n_layers=8)
    assert any(m.pipe > 1 for m in enumerate_layouts(deep, 8, 8))
    capped = enumerate_layouts(deep, 8, 8, max_pipe=1)
    assert capped and all(m.pipe == 1 for m in capped)


def test_enumerate_batch_divisibility_and_errors():
    # global batch 4 cannot shard over data*fsdp = 8
    assert not any(
        m.data * m.fsdp == 8 for m in enumerate_layouts(TINY, 8, 4)
    )
    with pytest.raises(ValueError, match="n_devices"):
        enumerate_layouts(TINY, 0, 8)
    # 7 devices: tensor=7 (64 % 7), pipe=7 (2 % 7) and dp=7 (batch 8 % 7)
    # are all illegal -> ranking raises loudly instead of silently 1x1x1x1
    with pytest.raises(ValueError, match="no legal"):
        rank_layouts(TINY, 7, global_batch_size=8)


# ---------------------------------------------------------------------------
# cost model shape
# ---------------------------------------------------------------------------


def test_param_count_tracks_presets():
    from photon_tpu.config import load_preset

    n125 = model_param_count(ModelConfig())
    assert 1.1e8 < n125 < 1.4e8  # the 125M recipe
    n1b = model_param_count(load_preset("mpt-1b").model)
    assert 1.2e9 < n1b < 1.5e9


def test_comm_grows_with_tensor_and_hbm_shrinks_with_fsdp():
    cfg = ModelConfig()  # 125M
    t1 = estimate_layout(cfg, MeshConfig(data=8), 256, microbatch=8)
    t2 = estimate_layout(cfg, MeshConfig(data=4, tensor=2), 256, microbatch=8)
    assert t2.breakdown["tensor_s"] > t1.breakdown["tensor_s"] == 0.0
    f1 = estimate_layout(cfg, MeshConfig(data=8), 256, microbatch=8)
    f8 = estimate_layout(cfg, MeshConfig(fsdp=8), 256, microbatch=8)
    assert f8.hbm_bytes_per_device < f1.hbm_bytes_per_device
    # pipeline bubble inflates compute
    deep = estimate_layout(cfg, MeshConfig(data=4, pipe=2), 256, microbatch=8)
    assert deep.bubble_frac > 0.0
    assert deep.compute_s > t1.compute_s


def test_ranking_small_model_prefers_data_parallel():
    best = rank_layouts(ModelConfig(), 8, 256, microbatch=8)[0]
    assert best.axes == (8, 1, 1, 1)
    assert best.fits


def test_ranking_big_model_shards_state_to_fit():
    """A 1.3B server state cannot live replicated on a 16 GB chip — the
    tuner must pick a layout that shards params/optimizer state (fsdp or
    tensor), exactly the heterogeneity story: the same model config gets
    a different layout on a different slice."""
    from photon_tpu.config import load_preset

    big = load_preset("mpt-1b").model
    ranked = rank_layouts(big, 8, 256, microbatch=4)
    best = ranked[0]
    assert best.fits
    assert best.mesh.fsdp * best.mesh.tensor * best.mesh.pipe > 1
    # pure dp8 is enumerated but cannot fit 1.3B x 16 bytes/param
    dp8 = next(e for e in ranked if e.axes == (8, 1, 1, 1))
    assert not dp8.fits


def test_federated_term_priced_with_pr7_machinery():
    cfg = ModelConfig()
    base = estimate_layout(cfg, MeshConfig(data=4), 256, microbatch=8)
    fed = estimate_layout(
        cfg, MeshConfig(data=4), 256, microbatch=8,
        n_clients=8, local_steps=10,
    )
    assert "federated_dcn_s" not in base.breakdown
    dcn = fed.breakdown["federated_dcn_s"]
    assert dcn > 0.0
    # q8 on the DCN leg shrinks the exchange term ~4x (the PR 7 model)
    fed_q8 = estimate_layout(
        cfg, MeshConfig(data=4), 256, microbatch=8,
        n_clients=8, local_steps=10, quantization="q8",
    )
    ratio = dcn / fed_q8.breakdown["federated_dcn_s"]
    assert 3.0 < ratio < 4.0
    # more local steps amortize the exchange
    fed_more = estimate_layout(
        cfg, MeshConfig(data=4), 256, microbatch=8,
        n_clients=8, local_steps=100,
    )
    assert fed_more.breakdown["federated_dcn_s"] < dcn


def test_entry_points():
    import jax

    mesh_cfg = autotune_mesh(TINY, n_devices=4, global_batch_size=8)
    assert isinstance(mesh_cfg, MeshConfig)
    assert mesh_cfg.size == 4
    best = autotune_layout(TINY, devices=jax.devices()[:4],
                           global_batch_size=8)
    assert best.mesh.size == 4
    with pytest.raises(ValueError, match="devices"):
        autotune_layout(TINY)


def test_trainer_autotunes_mesh_when_enabled():
    """The per-client entry point end to end: a Trainer built without an
    explicit mesh under photon.mesh_autotune derives its layout from the
    tuner over the local devices, and records the search for the
    server/layout_* KPIs."""
    from photon_tpu.config.schema import (
        Config, OptimizerConfig, SchedulerConfig, TrainConfig,
    )
    from photon_tpu.train.trainer import Trainer

    cfg = Config(
        model=TINY,
        optimizer=OptimizerConfig(name="adamw", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=100),
        train=TrainConfig(global_batch_size=8, device_microbatch_size=1),
    )
    cfg.photon.mesh_autotune = True
    trainer = Trainer(cfg, init_seed=0)
    tuned = trainer.layout_autotune
    assert tuned is not None
    assert tuned["search_s"] >= 0.0 and tuned["est_step_s"] > 0.0
    # 8 local CPU devices, tiny model -> pure data parallel
    assert trainer.mesh.shape["data"] == 8
    # an explicit mesh still wins (the collective runner's contract)
    from photon_tpu.parallel.mesh import single_device_mesh

    pinned = Trainer(cfg, mesh=single_device_mesh(), init_seed=0)
    assert pinned.layout_autotune is None
    assert pinned.mesh.devices.size == 1


def test_autotune_probe_never_kills_collective_runner_config():
    """The CollectiveFedRunner's layout probe is observability-only: a
    slice shape with no legal layout must degrade to a warning, not kill
    server construction (the loud error belongs to the Trainer path,
    which consumes the layout). Unit-covers the guarded call shape."""
    # heads=3/d_model=63-style indivisibility with an odd batch: nothing
    # legal at n_devices=7
    odd = dataclasses.replace(TINY, n_layers=3)
    with pytest.raises(ValueError, match="no legal"):
        rank_layouts(odd, 7, global_batch_size=9)
    # the runner wraps exactly this call in try/except ValueError — pin
    # that the exception type stays ValueError so the guard keeps working
    try:
        autotune_layout(odd, n_devices=7, global_batch_size=9)
    except ValueError:
        pass
    else:  # pragma: no cover
        pytest.fail("expected ValueError for an un-layoutable slice")


# ---------------------------------------------------------------------------
# AOT memory-analysis cross-check (abstract v5e, libtpu permitting)
# ---------------------------------------------------------------------------


def test_hbm_estimate_brackets_aot_memory_analysis():
    """ISSUE 14b validation: on the abstract v5e topology the tuner's HBM
    estimate and the REAL TPU compiler's memory analysis must agree within
    a loose factor for the 1B recipe at a layout the tuner marks as
    fitting — the estimate is a ranking device, not an allocator, but it
    must not be fantasy. Skips where the local libtpu cannot build
    topologies."""
    import jax
    from jax.sharding import NamedSharding

    from photon_tpu.config import load_preset
    from photon_tpu.models.mpt import MPTModel, init_params
    from photon_tpu.optim import build_optimizer
    from photon_tpu.parallel.mesh import make_mesh
    from photon_tpu.parallel.sharding import batch_spec, state_shardings
    from photon_tpu.parallel.topo import abstract_tpu_devices
    from photon_tpu.train.train_step import init_train_state, make_train_step

    try:
        devices = abstract_tpu_devices("v5e:2x2x1")
    except RuntimeError as e:
        pytest.skip(str(e))

    cfg = load_preset("mpt-1b")
    micro = 2
    # the PERF.md-proven family: fsdp shards the 1B state onto 4 chips
    layout = MeshConfig(fsdp=4)
    best = estimate_layout(
        cfg.model, layout, cfg.train.global_batch_size, microbatch=micro,
    )
    assert best.fits
    cfg.mesh = dataclasses.replace(best.mesh)
    cfg.model.attn_impl = "xla"
    cfg.train.device_microbatch_size = micro
    cfg.validate()
    mesh = make_mesh(cfg.mesh, devices=devices)
    model = MPTModel(cfg.model)
    tx, _ = build_optimizer(cfg.optimizer, cfg.scheduler)
    abstract_state = jax.eval_shape(
        lambda: init_train_state(model, tx, init_params(cfg.model, seed=0))
    )
    dp = cfg.mesh.data * cfg.mesh.fsdp
    n_micro = max(cfg.train.global_batch_size // (micro * dp), 1)
    step = make_train_step(model, tx, n_microbatches=n_micro,
                           loss_chunk_tokens=cfg.train.loss_chunk_tokens)
    shardings = state_shardings(abstract_state, mesh)
    batch_sh = NamedSharding(mesh, batch_spec(mesh))
    tokens = jax.ShapeDtypeStruct(
        (cfg.train.global_batch_size, cfg.model.max_seq_len), np.int32,
        sharding=batch_sh,
    )
    compiled = jax.jit(
        step, in_shardings=(shardings, batch_sh),
        out_shardings=(shardings, None), donate_argnums=0,
    ).lower(abstract_state, tokens).compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend provides no memory analysis")
    live = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
            + mem.output_size_in_bytes - mem.alias_size_in_bytes)
    est = best.hbm_bytes_per_device
    assert est / 4 < live < est * 4, (
        f"estimate {est / 2**30:.2f} GiB vs AOT {live / 2**30:.2f} GiB"
    )
    # and both respect the chip the tuner said it fits
    assert live < HardwareModel().hbm_bytes
