"""Telemetry plane tests (ISSUE 4): tracer semantics, trace-context
propagation across process boundaries (in-process, multiprocess, TCP),
chaos interaction (dropped/duplicated envelopes must not corrupt or
double-emit spans), the Perfetto export, the JSONL event log, the
Prometheus endpoint, and the KPI-name registry.

The fast half rides tier-1 (`make telemetry-smoke` runs the whole file
including the slow cross-process e2es).
"""

import json
import pathlib
import threading
import urllib.request

import pytest

from photon_tpu import telemetry
from photon_tpu.config.schema import TelemetryConfig
from photon_tpu.telemetry.events import EventLog, read_events_jsonl
from photon_tpu.telemetry.export import (
    load_chrome_trace,
    span_index,
    write_chrome_trace,
)
from photon_tpu.telemetry.spans import Tracer
from tests.test_federation import make_cfg, make_app


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with no process-global tracer installed
    (the same pollution-proofing discipline as chaos)."""
    telemetry.uninstall()
    yield
    telemetry.uninstall()


# ---------------------------------------------------------------------------
# Tracer unit semantics
# ---------------------------------------------------------------------------


def test_span_nesting_parents_and_trace_id():
    tr = Tracer("server")
    with tr.span("server/round_time", round=1) as outer:
        with tr.span("server/fit_round_time") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = tr.drain()
    assert [s["name"] for s in spans] == [
        "server/fit_round_time", "server/round_time"
    ]  # completion order: inner closes first
    assert spans[1]["parent_id"] is None
    assert spans[0]["attrs"] == {}
    assert spans[1]["attrs"] == {"round": 1}
    assert all(s["duration_s"] >= 0 for s in spans)


def test_attach_adopts_remote_parent():
    tr = Tracer("node0")
    with tr.attach(("deadbeef", "cafe0001")):
        with tr.span("client/fit_time") as sp:
            assert sp.trace_id == "deadbeef"
            assert sp.parent_id == "cafe0001"
    # stack unwound: a fresh span starts its own trace
    with tr.span("client/fit_time") as sp2:
        assert sp2.trace_id != "deadbeef"
    assert len(tr.drain()) == 2


def test_buffer_cap_drops_oldest_and_counts():
    tr = Tracer("server", max_buffered_spans=3)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert tr.dropped == 2
    assert [s["name"] for s in tr.drain()] == ["s2", "s3", "s4"]


def test_drain_ingest_roundtrip_preserves_proc():
    node = Tracer("node0", piggyback=True)
    with node.span("client/fit_time", cid=3):
        pass
    shipped = node.drain()
    assert node.drain() == []  # drained means drained
    server = Tracer("server")
    assert server.ingest(shipped) == 1
    merged = server.snapshot()
    assert merged[0]["proc"] == "node0"
    assert merged[0]["attrs"]["cid"] == 3
    # malformed shipped spans are skipped, never raise
    assert server.ingest([{"bogus": 1}, None]) == 0


def test_ingest_dedups_duplicated_shipments():
    """A chaos-duplicated reply frame ships the IDENTICAL drained list
    twice — possibly draining in a later scheduling window where mid-level
    dedup can't see it. The merge point drops the repeats for spans (by
    span_id) and events (by event id)."""
    node = Tracer("node0", piggyback=True)
    with node.span("client/fit_time", cid=1):
        pass
    shipped = node.drain()
    server = Tracer("server")
    assert server.ingest(shipped) == 1
    assert server.ingest(list(shipped)) == 0  # duplicate frame
    assert len(server.snapshot()) == 1

    nlog = EventLog("node0")
    nlog.emit("tcp/reconnect", {"reconnects": 1})
    sev = nlog.drain()
    slog = EventLog("server")
    assert slog.ingest(sev) == 1
    assert slog.ingest(list(sev)) == 0
    assert len(slog.snapshot()) == 1


def test_span_threads_have_independent_stacks():
    tr = Tracer("server")
    seen = {}

    def worker():
        # no context on this thread: new trace, no parent
        with tr.span("t2") as sp:
            seen["t2"] = (sp.trace_id, sp.parent_id)

    with tr.span("t1") as sp1:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert seen["t2"][0] != sp1.trace_id
        assert seen["t2"][1] is None


def test_install_disabled_is_none_and_span_is_noop():
    assert telemetry.install(TelemetryConfig(enabled=False), scope="x") is None
    assert telemetry.active() is None
    with telemetry.span("anything", round=1):  # shared null context
        assert telemetry.current_context() is None
    telemetry.emit_event("nothing")  # must not raise


# ---------------------------------------------------------------------------
# Event log + exporter
# ---------------------------------------------------------------------------


def test_event_log_write_through_and_correlation(tmp_path):
    path = tmp_path / "tel" / "events.jsonl"
    log = EventLog("server", path=str(path))
    log.emit("membership/transition", {"node": "node0", "from": "new", "to": "live"})
    log.emit("chaos/tcp_drop", {"scope": "node1"}, ctx=("abcd", "ef01"))
    log.close()
    events = read_events_jsonl(str(path))
    assert [e["kind"] for e in events] == ["membership/transition", "chaos/tcp_drop"]
    assert events[0]["proc"] == "server"
    assert events[1]["trace_id"] == "abcd" and events[1]["span_id"] == "ef01"
    assert all("ts" in e for e in events)


def test_event_log_buffered_drain_ingest():
    node = EventLog("node0")  # no path: buffer mode
    node.emit("tcp/reconnect", {"reconnects": 1})
    shipped = node.drain()
    assert node.drain() == []
    server = EventLog("server")
    assert server.ingest(shipped) == 1
    assert server.snapshot()[0]["proc"] == "node0"


def test_chrome_trace_export_structure(tmp_path):
    tr = Tracer("server")
    with tr.span("server/round_time", round=2):
        with tr.span("server/fit_round_time"):
            pass
    events = [{"ts": 123.0, "kind": "chaos/tcp_drop", "proc": "node0",
               "attrs": {}, "trace_id": "t", "span_id": "s"}]
    path = write_chrome_trace(tmp_path / "trace.json", tr.snapshot(), events)
    doc = load_chrome_trace(path)
    evs = doc["traceEvents"]
    complete = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"server/round_time", "server/fit_round_time"}
    assert all(e["ts"] > 0 and e["dur"] >= 0 for e in complete)
    # lineage is walkable through args
    idx = span_index(doc)
    child = next(e for e in complete if e["name"] == "server/fit_round_time")
    assert idx[child["args"]["parent_id"]]["name"] == "server/round_time"
    # instant marker + process-name metadata
    assert any(e["ph"] == "i" and e["name"] == "chaos/tcp_drop" for e in evs)
    names = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert {"server", "node0"} <= names


# ---------------------------------------------------------------------------
# Prometheus endpoint
# ---------------------------------------------------------------------------


def test_prom_metrics_endpoint():
    from photon_tpu.metrics.history import History
    from photon_tpu.telemetry.prom import PromServer

    h = History()
    h.record(3, {"server/round_time": 1.5, "server/n_clients": 2.0})
    srv = PromServer(h, port=0)  # ephemeral bind
    port = srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
    finally:
        srv.close()
    assert "# TYPE photon_server_round_time gauge" in body
    assert 'photon_server_round_time 1.5' in body
    assert "photon_last_round 3" in body


# ---------------------------------------------------------------------------
# History wandb mirror (satellite): only coerced floats reach wandb
# ---------------------------------------------------------------------------


def test_history_wandb_mirrors_only_coerced_floats():
    from photon_tpu.metrics.history import History

    logged = []

    class FakeWandb:
        def log(self, d, step=None):
            logged.append((step, d))

    h = History(FakeWandb())
    h.record(1, {"server/round_time": 2.0, "server/junk": None,
                 "server/name": "not-a-float", "server/ok": "3.5"})
    assert logged == [(1, {"server/round_time": 2.0, "server/ok": 3.5})]
    assert h.latest("server/junk") is None  # local record agrees


# ---------------------------------------------------------------------------
# SpeedMonitor auto-detect (satellite)
# ---------------------------------------------------------------------------


def test_speed_monitor_auto_detects_peak_from_device_kind():
    from photon_tpu.config.schema import ModelConfig
    from photon_tpu.utils.profiling import (
        TPU_V4_PEAK_FLOPS,
        TPU_V5E_PEAK_FLOPS,
        SpeedMonitor,
    )

    sm = SpeedMonitor(ModelConfig(), device_kind="TPU v4", n_chips=2)
    assert sm.peak_flops_per_chip == TPU_V4_PEAK_FLOPS
    assert sm.peak == 2 * TPU_V4_PEAK_FLOPS
    # unknown kinds keep the documented default
    assert SpeedMonitor(ModelConfig(), device_kind="cpu").peak_flops_per_chip \
        == TPU_V5E_PEAK_FLOPS
    # explicit peak still wins
    assert SpeedMonitor(ModelConfig(), peak_flops=1e12).peak == 1e12
    out = sm.update(tokens=1000, seconds=0.5)
    assert out["throughput/tokens_per_sec"] == 2000.0
    assert out["throughput/mfu"] > 0


# ---------------------------------------------------------------------------
# Duplicate-delivery dedup: a chaos-duplicated envelope must not double-emit
# ---------------------------------------------------------------------------


class _ScriptedConn:
    """Connection double feeding a fixed envelope sequence to NodeAgent.serve."""

    def __init__(self, envelopes):
        self._in = list(envelopes)
        self.sent = []

    def recv(self):
        if not self._in:
            raise EOFError("script exhausted")
        return self._in.pop(0)

    def send(self, obj):
        self.sent.append(obj)


def test_duplicate_envelope_single_span_emission(tmp_path):
    """The same FitIns delivered twice (chaos tcp_duplicate) runs ONE fit:
    one reply on the wire, one set of client spans piggybacked — the
    duplicate is consumed with no telemetry side effects."""
    from photon_tpu.federation import NodeAgent, ParamTransport
    from photon_tpu.federation.messages import Envelope, FitIns

    cfg = make_cfg(tmp_path, n_rounds=1)
    cfg.photon.telemetry.enabled = True
    agent = NodeAgent(cfg, "node0", lambda: ParamTransport("inline"))
    telemetry.install(cfg.photon.telemetry, scope="node0", piggyback=True)

    ptr = agent.runtime.transport.put(
        "bcast", *_tiny_params(cfg)
    )
    fit = FitIns(server_round=1, cids=[0], params=ptr, local_steps=1,
                 server_steps_cumulative=0)
    env = Envelope(fit, msg_id=7, trace=("feedc0de", "00000001"))
    conn = _ScriptedConn([env, env])  # duplicate delivery
    assert agent.serve(conn) is False  # script exhaustion = EOF
    assert len(conn.sent) == 1  # one reply despite two deliveries
    res = conn.sent[0].msg[0]
    assert res.error is None, res.error
    assert res.spans, "client spans must piggyback on the FitRes"
    fit_spans = [s for s in res.spans if s["name"] == "client/fit"]
    assert len(fit_spans) == 1  # no double emission
    assert fit_spans[0]["trace_id"] == "feedc0de"
    assert fit_spans[0]["parent_id"] == "00000001"
    span_ids = [s["span_id"] for s in res.spans]
    assert len(span_ids) == len(set(span_ids))


def _tiny_params(cfg):
    from photon_tpu.codec import params_to_ndarrays
    from photon_tpu.models.mpt import init_params

    return params_to_ndarrays(init_params(cfg.model, seed=0))


def test_dropped_envelope_then_retry_keeps_spans_clean(tmp_path):
    """A chaos-dropped FitIns manifests node-side as silence followed by a
    RETRY under a fresh msg_id (the server's timeout path). The retry must
    produce exactly one clean fit-span set — the drop corrupts nothing."""
    from photon_tpu.federation import NodeAgent, ParamTransport
    from photon_tpu.federation.messages import Envelope, FitIns

    cfg = make_cfg(tmp_path, n_rounds=1)
    cfg.photon.telemetry.enabled = True
    agent = NodeAgent(cfg, "node0", lambda: ParamTransport("inline"))
    telemetry.install(cfg.photon.telemetry, scope="node0", piggyback=True)
    ptr = agent.runtime.transport.put("bcast", *_tiny_params(cfg))
    fit = FitIns(server_round=1, cids=[0], params=ptr, local_steps=1,
                 server_steps_cumulative=0)
    # msg_id 8 = the retry; msg_id 7 (the dropped original) never arrives
    conn = _ScriptedConn([Envelope(fit, msg_id=8, trace=("feedc0de", "2"))])
    agent.serve(conn)
    res = conn.sent[0].msg[0]
    assert res.error is None, res.error
    assert len([s for s in res.spans if s["name"] == "client/fit"]) == 1
    ids = [s["span_id"] for s in res.spans]
    assert len(ids) == len(set(ids))
    assert telemetry.active().current_context() is None  # stack unwound


def test_socketconn_drop_emits_no_send_span():
    """A frame the chaos injector drops never hits the wire — and never
    emits a tcp/send span either (a phantom transport leg on the timeline
    would be corruption); the next successful send records normally."""
    import socket

    from photon_tpu import chaos as chaos_mod
    from photon_tpu.config.schema import ChaosConfig
    from photon_tpu.federation.messages import Envelope, Query
    from photon_tpu.federation.tcp import SocketConn

    telemetry.install(TelemetryConfig(enabled=True), scope="server")
    a, b = socket.socketpair()
    tx, rx = SocketConn(a), SocketConn(b)
    try:
        chaos_mod.install(
            ChaosConfig(enabled=True, seed=1234, tcp_drop_p=1.0), scope="t"
        )
        tx.send(Envelope(Query("ping"), 1))  # dropped
        assert [s["name"] for s in telemetry.active().snapshot()] == []
        chaos_mod.uninstall()
        tx.send(Envelope(Query("ping"), 2))  # delivered
        assert rx.recv().msg_id == 2
        names = [s["name"] for s in telemetry.active().snapshot()]
        assert names.count("tcp/send") == 1
        assert names.count("tcp/recv") == 1
    finally:
        chaos_mod.uninstall()
        tx.close(); rx.close()


# ---------------------------------------------------------------------------
# In-process end-to-end smoke (rides tier-1): merged trace + event log +
# KPI registry from one 1-round run
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("traced_run")
    cfg = make_cfg(tmp, n_rounds=1, eval_interval_rounds=1)
    cfg.photon.telemetry.enabled = True
    cfg.photon.checkpoint = True
    cfg.validate()
    app = make_app(cfg, tmp, with_ckpt=True)
    history = app.run()
    app.driver.shutdown()
    tdir = pathlib.Path(app.telemetry_dir)
    trace = load_chrome_trace(tdir / f"trace-{cfg.run_uuid}.json")
    events = read_events_jsonl(str(tdir / f"events-{cfg.run_uuid}.jsonl"))
    telemetry.uninstall()
    return cfg, history, trace, events


def test_traced_run_merged_timeline(traced_run):
    _, _, trace, events = traced_run
    complete = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in complete}
    # server phases AND client phases in ONE file
    assert {"server/round", "server/fit_round_time",
            "server/broadcast_pre_time", "server/checkpoint_time",
            "client/fit", "client/train", "client/encode"} <= names
    # every client fit span sits under a server round span
    idx = span_index(trace)
    rounds = [e for e in complete if e["name"] == "server/round"]
    round_ids = {e["args"]["span_id"] for e in rounds}
    fits = [e for e in complete if e["name"] == "client/fit"]
    assert fits
    for f in fits:
        anc, cur = set(), f
        while cur["args"].get("parent_id") in idx:
            cur = idx[cur["args"]["parent_id"]]
            anc.add(cur["args"]["span_id"])
        assert anc & round_ids, f"fit span not parented under a round span"
    # event log carries a membership transition (new node → live)
    kinds = {e["kind"] for e in events}
    assert "membership/transition" in kinds


def test_traced_run_parses_as_perfetto_json(traced_run):
    _, _, trace, _ = traced_run
    # contract perfetto/chrome relies on: top-level traceEvents, usec ts
    assert isinstance(trace["traceEvents"], list)
    for ev in trace["traceEvents"]:
        assert "ph" in ev and "name" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    assert json.dumps(trace)  # round-trips


def test_metric_registry_covers_runtime_names(traced_run):
    """Every server/* and client/* metric name History saw at runtime is a
    declared constant in utils/profiling.py (or a declared dynamic family)
    — no more stringly-typed KPI drift (ISSUE 4 satellite)."""
    from photon_tpu.utils.profiling import is_registered_metric

    _, history, _, _ = traced_run
    runtime = [k for k in history.rounds
               if k.startswith(("server/", "client/"))]
    assert runtime, "run recorded no prefixed KPIs?"
    unregistered = sorted(k for k in runtime if not is_registered_metric(k))
    assert not unregistered, (
        f"metric names recorded at runtime but not declared in "
        f"utils/profiling.py: {unregistered}"
    )


def test_registry_constants_are_unique():
    from photon_tpu.utils import profiling

    names = [v for k, v in vars(profiling).items()
             if isinstance(v, str) and not k.startswith("_")
             and (v.startswith("server/") or v.startswith("client/")
                  or v.startswith("serve/") or v.startswith("router/"))]
    assert len(names) == len(set(names)), "duplicate KPI constants"


def test_registry_covers_serve_names():
    """The serving plane's KPI vocabulary (ISSUE 5 satellite) is declared
    in the same registry as the training plane's."""
    from photon_tpu.utils.profiling import registered_metric_names

    names = registered_metric_names()
    for expect in ("serve/ttft_s", "serve/tokens_per_s", "serve/queue_depth",
                   "serve/slot_occupancy", "serve/evictions", "serve/rejected"):
        assert expect in names, expect


def test_registry_covers_fleet_router_names():
    """The fleet router's KPI vocabulary (ISSUE 16 satellite) rides the
    same registry — kpi-lint stays exit-0 for router/* emit sites."""
    from photon_tpu.utils.profiling import registered_metric_names

    names = registered_metric_names()
    for expect in ("router/requests_total", "router/reroutes_total",
                   "router/replicas_live", "serve/fleet_replicas",
                   "serve/fleet_rolling_swaps_total"):
        assert expect in names, expect


def test_telemetry_disabled_run_writes_nothing(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=1)
    app = make_app(cfg, tmp_path)
    app.run()
    app.driver.shutdown()
    assert telemetry.active() is None
    assert not pathlib.Path(app.telemetry_dir).exists()


# ---------------------------------------------------------------------------
# Cross-process propagation (slow): multiprocess + TCP round-trips
# ---------------------------------------------------------------------------


def _walk_to_round(idx, span_ev):
    cur = span_ev
    while cur["args"].get("parent_id") in idx:
        cur = idx[cur["args"]["parent_id"]]
        if cur["name"] == "server/round":
            return cur
    return None


@pytest.mark.slow
def test_multiprocess_trace_propagation_with_chaos(tmp_path):
    """The acceptance-criteria run: 2 rounds over a REAL spawned node
    process with chaos store faults on. The merged Perfetto JSON must show
    client fit spans (proc=node0) parented under the server round spans
    across the process boundary; the JSONL event log must carry a
    membership transition and an injected-fault event with trace
    correlation."""
    from photon_tpu.checkpoint import FileStore
    from photon_tpu.federation import MultiprocessDriver, ParamTransport, ServerApp

    cfg = make_cfg(tmp_path, n_rounds=2, n_total_clients=2,
                   n_clients_per_round=2, local_steps=1)
    cfg.photon.comm_stack.shm = False
    cfg.photon.comm_stack.objstore = True
    cfg.photon.telemetry.enabled = True
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.store_slow_p = 1.0
    cfg.photon.chaos.store_slow_max_s = 0.01
    driver = MultiprocessDriver(cfg, n_nodes=1, platform="cpu", n_cpu_devices=1)
    store = FileStore(cfg.photon.save_path + "/store")
    app = ServerApp(cfg, driver, ParamTransport("objstore", store=store))
    try:
        app.run()
    finally:
        driver.shutdown()

    tdir = pathlib.Path(app.telemetry_dir)
    trace = load_chrome_trace(tdir / f"trace-{cfg.run_uuid}.json")
    pid_names = {e["pid"]: e["args"]["name"]
                 for e in trace["traceEvents"] if e.get("ph") == "M"}
    complete = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    idx = span_index(trace)
    fits = [e for e in complete if e["name"] == "client/fit"]
    assert len(fits) >= 4  # 2 cids x 2 rounds
    for f in fits:
        assert pid_names[f["pid"]] == "node0"  # produced in the node process
        rnd = _walk_to_round(idx, f)
        assert rnd is not None, "fit span not under a server round span"
        assert pid_names[rnd["pid"]] == "server"
        assert f["args"]["trace_id"] == rnd["args"]["trace_id"]

    events = read_events_jsonl(str(tdir / f"events-{cfg.run_uuid}.jsonl"))
    kinds = {e["kind"] for e in events}
    assert "membership/transition" in kinds
    chaos_events = [e for e in events if e["kind"].startswith("chaos/")]
    assert chaos_events, "chaos fired but emitted no events"
    assert any(e.get("trace_id") for e in chaos_events), \
        "no chaos event carries trace correlation"


@pytest.mark.slow
def test_tcp_trace_propagation_under_duplicate_chaos(tmp_path):
    """TCP round-trip: trace context rides real socket envelopes, and with
    chaos duplicating EVERY frame (p=1.0) the node's msg_id dedup plus the
    driver's stale-mid guard keep the span stream clean — client fit spans
    carry the server round's trace_id, exactly one per fit, no duplicate
    span ids."""
    from photon_tpu import chaos as chaos_mod
    from photon_tpu.federation import ServerApp, ParamTransport
    from photon_tpu.federation.tcp import TcpServerDriver
    from tests.test_tcp_driver import _thread_node

    cfg = make_cfg(tmp_path, n_rounds=1, n_total_clients=2,
                   n_clients_per_round=2, local_steps=1, fit_timeout_s=30.0)
    cfg.photon.telemetry.enabled = True
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.tcp_duplicate_p = 1.0
    driver = TcpServerDriver("127.0.0.1", 0, expected_nodes=2)
    _threads = [_thread_node(cfg, f"node{i}", driver.port) for i in range(2)]
    driver.wait_for_nodes(timeout=30)
    app = ServerApp(cfg, driver, ParamTransport("inline"))
    try:
        history = app.run()
        assert history.latest("server/n_clients") == 2.0
    finally:
        driver.shutdown()
        chaos_mod.uninstall()

    tdir = pathlib.Path(app.telemetry_dir)
    trace = load_chrome_trace(tdir / f"trace-{cfg.run_uuid}.json")
    complete = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    idx = span_index(trace)
    rounds = [e for e in complete if e["name"] == "server/round"]
    assert len(rounds) == 1
    fits = [e for e in complete if e["name"] == "client/fit"]
    # exactly one fit span per cid: the duplicated FitIns frames were
    # deduplicated node-side, the duplicated replies server-side
    assert len(fits) == 2
    for f in fits:
        assert f["args"]["trace_id"] == rounds[0]["args"]["trace_id"]
        assert _walk_to_round(idx, f) is not None
    ids = [e["args"]["span_id"] for e in complete]
    assert len(ids) == len(set(ids)), "duplicate span ids in merged trace"
