"""The 1B recipe compiles under real multi-chip sharding — abstractly.

BASELINE.md's north star includes "scale to 1.3B across 8 TPU-slice
clients". Hardware for that doesn't exist here, but the whole sharded
program can be validated without materializing a single parameter:
``jax.eval_shape`` builds the abstract TrainState for the ACTUAL mpt-1b
preset (d2048 / 24L / 16H, seq 2048, vocab 50368, remat on, reference
``conf/llm_config/mpt-1b.yaml``), GSPMD shardings are derived for an
fsdp=4 x tensor=2 mesh, and the full train step (microbatch scan + chunked
CE + AdamW) is lowered and compiled AOT. XLA's memory analysis then bounds
the per-device footprint — the "does 1B fit on a 16 GB v5e slice" question
— with zero FLOPs executed.
"""

import jax
import numpy as np
import pytest

from photon_tpu.config import load_preset
from photon_tpu.config.schema import MeshConfig


@pytest.mark.slow
def test_1b_train_step_compiles_sharded():
    from jax.sharding import NamedSharding

    from photon_tpu.models.mpt import MPTModel, init_params
    from photon_tpu.optim import build_optimizer
    from photon_tpu.parallel.mesh import make_mesh
    from photon_tpu.parallel.sharding import batch_spec, state_shardings
    from photon_tpu.train.train_step import init_train_state, make_train_step

    cfg = load_preset("mpt-1b")
    cfg.mesh = MeshConfig(fsdp=4, tensor=2)
    cfg.model.attn_impl = "xla"  # pallas needs a real TPU; sharding is identical
    cfg.validate()

    mesh = make_mesh(cfg.mesh)
    model = MPTModel(cfg.model)
    tx, _ = build_optimizer(cfg.optimizer, cfg.scheduler)

    abstract_state = jax.eval_shape(
        lambda: init_train_state(model, tx, init_params(cfg.model, seed=0))
    )
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(abstract_state.params)
    )
    assert 1.2e9 < n_params < 1.5e9, f"{n_params:,} params is not the 1B recipe"

    dp = cfg.mesh.data * cfg.mesh.fsdp
    micro = cfg.train.device_microbatch_size  # 4, per the reference recipe
    n_micro = cfg.train.global_batch_size // (micro * dp)  # 512 / 16 = 32
    step = make_train_step(model, tx, n_microbatches=n_micro,
                           loss_chunk_tokens=cfg.train.loss_chunk_tokens)

    shardings = state_shardings(abstract_state, mesh)
    batch_sh = NamedSharding(mesh, batch_spec(mesh))
    tokens = jax.ShapeDtypeStruct(
        (cfg.train.global_batch_size, cfg.model.max_seq_len), np.int32,
        sharding=batch_sh,
    )
    jitted = jax.jit(
        step, in_shardings=(shardings, batch_sh), out_shardings=(shardings, None),
        donate_argnums=0,
    )
    compiled = jitted.lower(abstract_state, tokens).compile()

    # XLA's own accounting: sharded params + optimizer state + activations
    # must fit a 16 GB v5e chip with headroom for the runtime. (On the CPU
    # backend the analysis covers one device's share of the SPMD program.)
    mem = compiled.memory_analysis()
    if mem is not None:  # backend-dependent availability
        # donated state aliases into the output (alias_size covers it), so
        # live bytes = args + temps + any non-aliased output
        per_dev_gb = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
                      + mem.output_size_in_bytes - mem.alias_size_in_bytes) / 2**30
        # measured (PERF.md "1B per-device memory"): ~12.6 GiB at the
        # reference recipe (micro 4, remat, chunked CE) on fsdp4 x tensor2 —
        # fits a 16 GiB v5e with runtime headroom. fsdp8-without-TP is the
        # config that does NOT fit (~35 GiB: full-width gathered weights).
        assert per_dev_gb < 14.0, f"{per_dev_gb:.1f} GiB/device exceeds v5e headroom"
