"""The 1B/3B/7B recipes compile under real multi-chip sharding — abstractly.

BASELINE.md's north star includes "scale to 1.3B across 8 TPU-slice
clients". Hardware for that doesn't exist here, but the whole sharded
program is validated without materializing a single parameter:
``jax.eval_shape`` builds the abstract TrainState for the ACTUAL preset
(reference ``conf/llm_config/mpt-1b.yaml`` etc.), GSPMD shardings are
derived for the mesh, and the full train step (microbatch scan + chunked CE
+ optimizer) is lowered and compiled AOT. XLA's memory analysis then bounds
the per-device footprint — the "does it fit a 16 GiB v5e" question — with
zero FLOPs executed. The fitting meshes and the widen-tensor-not-fsdp rule
they expose are recorded in PERF.md ("1B per-device memory").
"""

import jax
import numpy as np
import pytest

from photon_tpu.config import load_preset
from photon_tpu.config.schema import MeshConfig


@pytest.mark.parametrize(
    "preset,mesh_kw,micro,params_range",
    [
        # reference recipe micro=4 measures 12.6 GiB/device on 8 chips
        ("mpt-1b", dict(fsdp=4, tensor=2), 4, (1.2e9, 1.5e9)),
        # 3B fits ONE 8-chip v5e slice at micro 2
        ("mpt-3b", dict(fsdp=4, tensor=2), 2, (2.4e9, 2.9e9)),
        # 7B needs 32 chips; fsdp8xtp4 fits where fsdp16xtp2 (36 GiB) won't
        pytest.param("mpt-7b", dict(fsdp=8, tensor=4), 2, (6.2e9, 7.2e9),
                     marks=pytest.mark.slow),  # real-TPU-compiler compile, ~2 min
        # llama family at 1B scale: RoPE/RMSNorm/SwiGLU/GQA params shard
        # under the same rules (separate q/k/v + gate/up projections)
        ("llama-1b", dict(fsdp=4, tensor=2), 2, (1.0e9, 1.2e9)),
    ],
    ids=["1b-8dev", "3b-8dev", "7b-32dev", "llama1b-8dev"],
)
def test_preset_train_step_compiles_sharded(preset, mesh_kw, micro, params_range):
    from jax.sharding import NamedSharding

    from photon_tpu.models.mpt import MPTModel, init_params
    from photon_tpu.optim import build_optimizer
    from photon_tpu.parallel.mesh import make_mesh
    from photon_tpu.parallel.sharding import batch_spec, state_shardings
    from photon_tpu.train.train_step import init_train_state, make_train_step

    cfg = load_preset(preset)
    cfg.mesh = MeshConfig(**mesh_kw)
    n_dev = 1
    for v in cfg.mesh.axis_sizes().values():
        n_dev *= v
    cfg.model.attn_impl = "xla"  # sharding identical; keeps the 8-dev cases fast
    cfg.train.device_microbatch_size = micro
    cfg.validate()

    if n_dev > len(jax.devices()):
        # conftest pins 8 virtual CPU devices; larger meshes compile against
        # an ABSTRACT TPU topology instead (photon_tpu.parallel.topo, shared
        # with scripts/aot_compile_check.py), which also makes the memory
        # bound below the real TPU compiler's accounting
        from photon_tpu.parallel.topo import abstract_tpu_devices

        shape = {16: "4x4", 32: "4x8"}.get(n_dev)
        if shape is None:
            pytest.skip(f"no abstract topology mapped for {n_dev} devices")
        try:
            devices = abstract_tpu_devices(f"v5e:{shape}x1")
        except RuntimeError as e:
            pytest.skip(str(e))
        mesh = make_mesh(cfg.mesh, devices=devices)
    else:
        mesh = make_mesh(cfg.mesh)
    model = MPTModel(cfg.model)
    tx, _ = build_optimizer(cfg.optimizer, cfg.scheduler)

    abstract_state = jax.eval_shape(
        lambda: init_train_state(model, tx, init_params(cfg.model, seed=0))
    )
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(abstract_state.params)
    )
    lo, hi = params_range
    assert lo < n_params < hi, f"{n_params:,} params is not the {preset} recipe"

    dp = cfg.mesh.data * cfg.mesh.fsdp
    n_micro = max(cfg.train.global_batch_size // (micro * dp), 1)
    step = make_train_step(model, tx, n_microbatches=n_micro,
                           loss_chunk_tokens=cfg.train.loss_chunk_tokens)

    shardings = state_shardings(abstract_state, mesh)
    batch_sh = NamedSharding(mesh, batch_spec(mesh))
    tokens = jax.ShapeDtypeStruct(
        (cfg.train.global_batch_size, cfg.model.max_seq_len), np.int32,
        sharding=batch_sh,
    )
    jitted = jax.jit(
        step, in_shardings=(shardings, batch_sh), out_shardings=(shardings, None),
        donate_argnums=0,
    )
    compiled = jitted.lower(abstract_state, tokens).compile()

    mem = compiled.memory_analysis()
    if mem is not None:  # backend-dependent availability
        # donated state aliases into the output (alias_size covers it), so
        # live bytes = args + temps + any non-aliased output
        per_dev_gb = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
                      + mem.output_size_in_bytes - mem.alias_size_in_bytes) / 2**30
        assert per_dev_gb < 14.0, f"{per_dev_gb:.1f} GiB/device exceeds v5e headroom"
