"""``parallel/topo.abstract_tpu_devices`` error paths (ISSUE 14
satellite): the happy path is exercised indirectly by the AOT compile
checks, but the failure modes — malformed topology strings, a raising
``get_topology_desc`` — must degrade cleanly AND restore every env var the
helper overrode (a leaked TPU_* var would poison later backend inits in
the same process)."""

import os

import pytest

from photon_tpu.parallel import topo

_ENV_KEYS = ("TPU_SKIP_MDS_QUERY", "TPU_ACCELERATOR_TYPE",
             "TPU_WORKER_HOSTNAMES", "TPU_TOPOLOGY")


def _env_snapshot():
    return {k: os.environ.get(k) for k in _ENV_KEYS}


def test_malformed_topology_string_rejected():
    with pytest.raises(ValueError, match="must look like"):
        topo.abstract_tpu_devices("v5e-2x2x1")  # no colon
    with pytest.raises(ValueError, match="must look like"):
        topo.abstract_tpu_devices("2x2x1")


def test_malformed_string_leaves_env_untouched():
    before = _env_snapshot()
    with pytest.raises(ValueError):
        topo.abstract_tpu_devices("garbage")
    assert _env_snapshot() == before


def test_env_restored_after_raising_get_topology_desc(monkeypatch):
    """A get_topology_desc that raises (libtpu missing/incompatible) must
    surface as the documented RuntimeError AND restore the env overrides
    in the finally block — including a pre-existing value the helper
    overwrote."""
    from jax.experimental import topologies

    monkeypatch.setenv("TPU_TOPOLOGY", "preexisting-sentinel")
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    before = _env_snapshot()

    seen_env = {}

    def boom(*a, **kw):
        seen_env.update(_env_snapshot())
        raise OSError("libtpu exploded")

    monkeypatch.setattr(topologies, "get_topology_desc", boom)
    with pytest.raises(RuntimeError, match="unavailable") as ei:
        topo.abstract_tpu_devices("v5e:2x2x1")
    # the cause is chained for debuggability
    assert isinstance(ei.value.__cause__, OSError)
    # the overrides WERE in place during the call...
    assert seen_env["TPU_TOPOLOGY"] == "2x2"
    assert seen_env["TPU_WORKER_HOSTNAMES"] == "localhost"
    # ...and are fully restored after: overwritten values come back,
    # helper-created keys are removed again
    assert _env_snapshot() == before
    assert os.environ["TPU_TOPOLOGY"] == "preexisting-sentinel"
    assert "TPU_WORKER_HOSTNAMES" not in os.environ


def test_v5e_trailing_x1_sugar_stripped_exactly_once(monkeypatch):
    """"2x4x1" == "2x4" for the 2-D v5e generation — but only a literal
    trailing x1 dimension is stripped, never a substring."""
    from jax.experimental import topologies

    seen = []

    def record(*a, **kw):
        seen.append(os.environ.get("TPU_TOPOLOGY"))
        raise OSError("stop here")

    monkeypatch.setattr(topologies, "get_topology_desc", record)
    for spec, expect in [("v5e:2x4x1", "2x4"), ("v5e:2x1", "2x1"),
                         ("v5e:1x1", "1x1")]:
        with pytest.raises(RuntimeError):
            topo.abstract_tpu_devices(spec)
        assert seen[-1] == expect, spec
