"""Federation-layer tests: end-to-end fed rounds (in-process driver),
sampling determinism, failure budget, checkpoint/resume determinism,
broadcast semantics. The multiprocess driver gets its own slower test.

Reference oracles (SURVEY.md §4): norm telemetry presence, deterministic
client sampling incl. resume fast-forward, TooManyFailuresError budget.
"""

import numpy as np
import pytest

from photon_tpu.checkpoint import FileStore, ServerCheckpointManager
from photon_tpu.config.schema import (
    Config,
    FLConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    PhotonConfig,
    SchedulerConfig,
    TrainConfig,
)
from photon_tpu.federation import (
    InProcessDriver,
    NodeAgent,
    ParamTransport,
    ServerApp,
    TooManyFailuresError,
)


def make_cfg(tmp_path, **fl_kw) -> Config:
    fl = dict(
        n_total_clients=4, n_clients_per_round=2, n_rounds=3, local_steps=2,
        strategy_name="nesterov", server_learning_rate=1.0, server_momentum=0.0,
        eval_interval_rounds=0, sample_seed=99,
    )
    fl.update(fl_kw)
    cfg = Config(
        run_uuid="testrun",
        model=ModelConfig(
            d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
            attn_impl="xla", compute_dtype="float32",
        ),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=1000),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4, eval_batches=2),
        fl=FLConfig(**fl),
        photon=PhotonConfig(save_path=str(tmp_path / "save"), checkpoint=False),
    )
    cfg.dataset.synthetic = True
    return cfg.validate()


def make_app(cfg, tmp_path, n_nodes=2, with_ckpt=False):
    transport = ParamTransport("inline")

    def make_agent(node_id):
        return NodeAgent(cfg, node_id, lambda: ParamTransport("inline"))

    driver = InProcessDriver(cfg, make_agent, n_nodes=n_nodes)
    ckpt = None
    if with_ckpt:
        ckpt = ServerCheckpointManager(FileStore(tmp_path / "ckpt"), cfg.run_uuid)
    return ServerApp(cfg, driver, transport, ckpt_mgr=ckpt)


def test_fed_rounds_end_to_end(tmp_path):
    cfg = make_cfg(tmp_path)
    app = make_app(cfg, tmp_path)
    history = app.run()
    # three rounds recorded with the reference KPI names — server-side AND
    # the client-side timing decomposition (BASELINE.md instrumentation row:
    # ``llm_client_functions.py:161-209``, ``node_manager_app.py:463-468``)
    for key in ("server/round_time", "server/fit_round_time", "server/broadcast_pre_time",
                "server/n_clients", "server/pseudo_grad_norm",
                "node_training_time_s", "client/fit_time", "client/fit_init_time",
                "client/fit_set_parameters_time"):
        assert len(history.series(key)) == 3, key
    assert app.server_steps_cumulative == 3 * cfg.fl.local_steps
    # client states merged for trained cids
    assert all(st["steps_cumulative"] > 0 for st in app.client_states.values())
    app.driver.shutdown()


@pytest.mark.slow
def test_training_actually_changes_params(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=2)
    app = make_app(cfg, tmp_path)
    before = [a.copy() for a in app.strategy.current_parameters]
    app.run(n_rounds=2)
    after = app.strategy.current_parameters
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    app.driver.shutdown()


@pytest.mark.slow
def test_sampling_deterministic(tmp_path):
    cfg = make_cfg(tmp_path)
    a = make_app(cfg, tmp_path)
    b = make_app(cfg, tmp_path)
    sa = [a._sample_clients() for _ in range(5)]
    sb = [b._sample_clients() for _ in range(5)]
    assert sa == sb
    assert len(set(map(tuple, sa))) > 1  # actually varies round to round
    a.driver.shutdown(); b.driver.shutdown()


@pytest.mark.slow
def test_failure_budget(tmp_path):
    cfg = make_cfg(tmp_path, accept_failures_cnt=0)
    app = make_app(cfg, tmp_path)

    # sabotage: all agents raise for cid 0 via a broken runtime fit
    for agent in app.driver._agents.values():
        orig_fit = agent.runtime.fit

        def fit(ins, cid, _orig=orig_fit):
            if cid == app._doomed:
                from photon_tpu.federation.messages import FitRes
                return FitRes(ins.server_round, cid, None, error="boom")
            return _orig(ins, cid)

        agent.runtime.fit = fit

    app._doomed = -1  # nobody fails
    app.broadcast_parameters(1)
    app.fit_round(1)

    # choose a cid guaranteed to be sampled next round: replay the PRNG
    import random as _r
    rng = _r.Random(cfg.fl.sample_seed)
    for _ in range(app._rounds_sampled + 1):
        next_cids = sorted(rng.sample(range(cfg.fl.n_total_clients), cfg.fl.n_clients_per_round))
    app._doomed = next_cids[0]
    app.broadcast_parameters(2)
    with pytest.raises(TooManyFailuresError):
        app.fit_round(2)
    app.driver.shutdown()


@pytest.mark.slow
def test_failed_cid_retries_once_then_counts(tmp_path):
    """A cid that fails once but succeeds on retry must not raise."""
    cfg = make_cfg(tmp_path, accept_failures_cnt=0, n_clients_per_round=2)
    app = make_app(cfg, tmp_path)
    calls = {"n": 0}
    agents = list(app.driver._agents.values())
    for agent in agents:
        orig_fit = agent.runtime.fit

        def fit(ins, cid, _orig=orig_fit):
            if calls["n"] == 0:
                calls["n"] += 1
                from photon_tpu.federation.messages import FitRes
                return FitRes(ins.server_round, cid, None, error="flaky")
            return _orig(ins, cid)

        agent.runtime.fit = fit
    app.broadcast_parameters(1)
    metrics = app.fit_round(1)
    assert metrics["server/n_clients"] == 2  # both cids aggregated despite one flake
    app.driver.shutdown()


@pytest.mark.slow
def test_eval_round(tmp_path):
    cfg = make_cfg(tmp_path, eval_interval_rounds=1, n_rounds=1)
    app = make_app(cfg, tmp_path)
    history = app.run()
    assert history.latest("server/eval_loss") is not None
    assert history.latest("server/eval_loss") > 0
    app.driver.shutdown()


@pytest.mark.slow
def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Golden determinism oracle: run 4 rounds straight vs 2 + resume + 2.
    Parameters and the sampled-client sequence must match exactly.

    ``reset_optimizer`` keeps client optimizer state round-local (the
    non-reset path needs client checkpoints to survive a node restart);
    loader positions resume via the client-state sample counters."""
    cfg_a = make_cfg(tmp_path / "a", n_rounds=4, fit_config={"reset_optimizer": True})
    cfg_a.photon.checkpoint = True
    app_a = make_app(cfg_a, tmp_path / "a", with_ckpt=True)
    app_a.run()
    final_a = [a.copy() for a in app_a.strategy.current_parameters]
    app_a.driver.shutdown()

    cfg_b = make_cfg(tmp_path / "b", n_rounds=2, fit_config={"reset_optimizer": True})
    cfg_b.photon.checkpoint = True
    app_b = make_app(cfg_b, tmp_path / "b", with_ckpt=True)
    app_b.run()
    app_b.driver.shutdown()

    cfg_c = make_cfg(tmp_path / "b", n_rounds=4, fit_config={"reset_optimizer": True})
    cfg_c.photon.checkpoint = True
    cfg_c.photon.resume_round = -1
    app_c = make_app(cfg_c, tmp_path / "b", with_ckpt=True)
    assert app_c.try_resume() == 2
    assert app_c.start_round == 3
    app_c.cfg.photon.resume_round = None  # already resumed
    for rnd in range(3, 5):
        app_c.broadcast_parameters(rnd)
        m = app_c.fit_round(rnd)
        app_c.save_checkpoint(rnd)
        app_c.history.record(rnd, m)
    final_c = app_c.strategy.current_parameters
    app_c.driver.shutdown()

    for x, y in zip(final_a, final_c):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_refresh_period_broadcast(tmp_path):
    cfg = make_cfg(tmp_path, n_rounds=3)
    cfg.photon.refresh_period = 2
    app = make_app(cfg, tmp_path)
    history = app.run()
    assert len(history.series("server/round_time")) == 3
    app.driver.shutdown()
