"""Ragged paged attention + mixed chunked-prefill batches (ISSUE 12).

Contract layers:

1. **kernel unit parity** — the fused Pallas kernel (interpret mode)
   vs the dense reference over the same live view, across GQA/ALiBi
   shapes, pow2 token buckets and recycled-block tables. EPSILON tier:
   the online softmax reorders the fp32 accumulation, so the bound is
   pinned (``KERNEL_PARITY.json`` discipline), not bit-exact.
2. **mixed-step bit-parity** — the unified chunked-prefill/decode
   program's GATHER path vs the contiguous ``models/decode.py`` oracle,
   per step, ``assert_array_equal``: chunked prefill across several
   chunk budgets, decode continuation, post-eviction recycled blocks,
   and prefix-cache-hit admissions, across mpt-wpe / mpt-alibi /
   llama-gqa.
3. **scheduler cadence** — a 4x-budget prompt is split across chunk
   steps and an in-flight decode emits a token on EVERY step of the
   split (the PR 5 carve-out let it stall for the whole prefill).
4. **config gating** — ``serve.attention_impl`` validation: bad values
   and ``ragged``-without-Pallas/interpret fail at validate(), not at
   the first decode step.
5. **no-retrace** — warm ragged bursts with chunked prompts, prefix
   hits and a live hot-swap compile nothing (the sentinel e2e).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.config.schema import Config

from tests._helpers import tiny_llama_config


def _serve_cfg(*, alibi=False, llama=False, n_slots=2, block_size=4,
               max_seq=32, max_new=8, budget=2048, prefix=False,
               attn="auto", interpret=False) -> Config:
    if llama:
        cfg = tiny_llama_config(n_kv_heads=2)
    else:
        cfg = Config()
        cfg.model.d_model = 32
        cfg.model.n_layers = 2
        cfg.model.n_heads = 4
        cfg.model.vocab_size = 96
        cfg.model.attn_impl = "xla"
        cfg.model.compute_dtype = "float32"
        cfg.model.alibi = alibi
        cfg.model.learned_pos_emb = not alibi
    cfg.model.max_seq_len = max_seq
    cfg.photon.serve.n_slots = n_slots
    cfg.photon.serve.block_size = block_size
    cfg.photon.serve.max_new_tokens = max_new
    cfg.photon.serve.prefill_token_budget = budget
    cfg.photon.serve.prefix_cache = prefix
    cfg.photon.serve.attention_impl = attn
    cfg.photon.serve.attention_interpret = interpret
    return cfg.validate()


def _offline_greedy(cfg, params, prompt, n):
    from photon_tpu.models.decode import make_cached_generate_fn

    buf = np.zeros((1, len(prompt) + n), np.int32)
    buf[0, : len(prompt)] = prompt
    fn = make_cached_generate_fn(cfg.model, params)
    t, _ = fn.many(jnp.asarray(buf), jnp.asarray([len(prompt)], np.int32), n)
    return [int(x) for x in np.asarray(t)[0, len(prompt):]]


def _rel(a, ref):
    a = np.asarray(a, np.float32)
    ref = np.asarray(ref, np.float32)
    return float(np.linalg.norm(a - ref) / (np.linalg.norm(ref) + 1e-12))


# ---------------------------------------------------------------------------
# 1. kernel unit parity (epsilon tier, KERNEL_PARITY discipline)
# ---------------------------------------------------------------------------

#: pinned epsilon for the fused online-softmax kernel vs the dense
#: reference, fp32 end to end (the online rescaling reorders the fp32
#: accumulation; observed ~1e-7, bound leaves one order of headroom)
RAGGED_KERNEL_EPS = 2e-6


@pytest.mark.parametrize("t", [1, 2, 4, 8])  # pow2 token buckets
@pytest.mark.parametrize("gqa,alibi", [(False, False), (False, True),
                                       (True, False)])
def test_kernel_parity_token_buckets(t, gqa, alibi):
    from photon_tpu.ops.attention import alibi_slopes
    from photon_tpu.ops.ragged_paged_attention import (
        live_view, ragged_paged_attention, ragged_reference_attention,
    )

    rng = np.random.default_rng(7)
    b, h, dh, bs, nb, n_ctx = 3, 4, 8, 4, 17, 4
    n_kv = 2 if gqa else h
    kp = jnp.asarray(rng.standard_normal((nb, bs, n_kv, dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((nb, bs, n_kv, dh)), jnp.float32)
    rows = jnp.asarray(rng.integers(0, nb, (b, n_ctx)), jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, t, h, dh)), jnp.float32)
    pos = jnp.asarray(rng.integers(0, n_ctx * bs, (b, t)), jnp.int32)
    slopes = alibi_slopes(h) if alibi else None
    kb, vb = live_view(kp, vp, rows)
    ref = ragged_reference_attention(q, kb, vb, pos, slopes=slopes)
    out = ragged_paged_attention(q, kp, vp, rows, pos, slopes=slopes,
                                 interpret=True)
    assert _rel(out, ref) < RAGGED_KERNEL_EPS, (t, gqa, alibi)


def test_kernel_parity_recycled_blocks():
    """A table whose entries point at shuffled, REUSED physical blocks
    (the post-eviction pool shape: stale bytes everywhere, shared ids
    across slots) — only positions <= each query's own position may
    contribute, and they do so identically to the dense reference."""
    from photon_tpu.ops.ragged_paged_attention import (
        live_view, ragged_paged_attention, ragged_reference_attention,
    )

    rng = np.random.default_rng(11)
    b, t, h, dh, bs, nb, n_ctx = 2, 2, 2, 8, 4, 6, 8
    kp = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    # deliberately overlapping rows (two slots sharing physical blocks —
    # the prefix-cache CoW shape) with trash-id tails
    rows = jnp.asarray([[0, 3, 3, 1, 5, 5, 5, 5],
                        [3, 0, 2, 4, 5, 5, 5, 5]], jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, t, h, dh)), jnp.float32)
    pos = jnp.asarray([[6, 13], [0, 30]], jnp.int32)
    kb, vb = live_view(kp, vp, rows)
    ref = ragged_reference_attention(q, kb, vb, pos)
    out = ragged_paged_attention(q, kp, vp, rows, pos, interpret=True)
    assert _rel(out, ref) < RAGGED_KERNEL_EPS


def test_reference_matches_full_width():
    """The live-width cut is bitwise-invisible to the reference math:
    scores past a query's position are masked to exactly-zero
    probability, so a wider walk changes nothing."""
    from photon_tpu.ops.ragged_paged_attention import (
        live_view, ragged_reference_attention,
    )

    rng = np.random.default_rng(3)
    b, t, h, dh, bs, nb = 2, 2, 2, 8, 4, 9
    kp = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    full = jnp.asarray(rng.integers(0, nb, (b, 8)), jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, t, h, dh)), jnp.float32)
    pos = jnp.asarray([[3, 7], [1, 6]], jnp.int32)  # all < 2 blocks
    kb2, vb2 = live_view(kp, vp, full[:, :2])
    kb8, vb8 = live_view(kp, vp, full)
    np.testing.assert_array_equal(
        np.asarray(ragged_reference_attention(q, kb2, vb2, pos)),
        np.asarray(ragged_reference_attention(q, kb8, vb8, pos)),
    )


# ---------------------------------------------------------------------------
# 2. mixed-step bit-parity vs the contiguous decoder
# ---------------------------------------------------------------------------


def _drive_chunked(cfg, params, prompt, chunk_cap, gen, *, impl="gather",
                   n_ctx=4):
    """Chunk-prefill ``prompt`` through mixed_chunk_step on a fresh pool,
    then greedily decode ``gen`` tokens; returns (per-emission logits
    list, the paged state). Slot 1 stays idle throughout (pad rows)."""
    from photon_tpu.serve.cache import (
        BlockAllocator, init_paged_state, install_row, mixed_chunk_step,
    )

    mc = cfg.model
    bs = cfg.photon.serve.block_size
    m = -(-mc.max_seq_len // bs)
    B = 2
    alloc = BlockAllocator(B * m)
    pst = init_paged_state(mc, B, B * m, bs, m)
    need = -(-(len(prompt) + gen) // bs)
    ids = alloc.alloc(need)
    row = np.full(m, B * m, np.int32)
    row[:need] = ids
    pst = install_row(pst, jnp.int32(0), jnp.asarray(row), jnp.int32(0))
    n = len(prompt)
    lengths = np.zeros(B, np.int32)
    emissions = []

    def bucket(cn):
        blocks = -(-cn // bs)
        return min(1 << (blocks - 1).bit_length(), m) * bs

    pos0 = 0
    interpret = impl == "ragged"
    while pos0 < n:
        cn = min(chunk_cap, n - pos0)
        tq = bucket(cn)
        tk = np.zeros((B, tq), np.int32)
        ps = np.zeros((B, tq), np.int32)
        qv = np.zeros((B, tq), bool)
        eo = np.zeros(B, np.int32)
        tk[0, :cn] = prompt[pos0:pos0 + cn]
        ps[0, :cn] = np.arange(pos0, pos0 + cn)
        qv[0, :cn] = True
        la = lengths.copy()
        la[0] = pos0 + cn
        if pos0 + cn == n:
            eo[0] = cn - 1
        logits, pst = mixed_chunk_step(
            params, pst, jnp.asarray(tk), jnp.asarray(ps), jnp.asarray(qv),
            jnp.asarray(eo), jnp.asarray(la), jnp.int32(0), mc,
            n_ctx=n_ctx, has_chunk=True, impl=impl, interpret=interpret,
        )
        lengths = la
        pos0 += cn
    emissions.append(np.asarray(logits[0]))
    for _ in range(gen):
        nxt = int(np.argmax(emissions[-1]))
        tk = np.zeros((B, 1), np.int32)
        ps = np.zeros((B, 1), np.int32)
        qv = np.zeros((B, 1), bool)
        eo = np.zeros(B, np.int32)
        tk[0, 0] = nxt
        ps[0, 0] = lengths[0]
        qv[0, 0] = True
        la = lengths.copy()
        la[0] += 1
        logits, pst = mixed_chunk_step(
            params, pst, jnp.asarray(tk), jnp.asarray(ps), jnp.asarray(qv),
            jnp.asarray(eo), jnp.asarray(la), jnp.int32(0), mc,
            n_ctx=n_ctx, has_chunk=False, impl=impl, interpret=interpret,
        )
        lengths = la
        emissions.append(np.asarray(logits[0]))
    return emissions, pst


def _oracle_logits(cfg, params, prompt, gen):
    """Contiguous models/decode.py logits stream: prefill emission + every
    greedy decode step (buffer sized to never overflow the one-hot write)."""
    from photon_tpu.models.decode import decode_step, prefill

    mc = cfg.model
    n = len(prompt)
    buf = np.zeros((1, n + gen + 1), np.int32)
    buf[0, :n] = prompt
    lo, st = prefill(params, jnp.asarray(buf), jnp.asarray([n], np.int32), mc)
    out = [np.asarray(lo[0])]
    for _ in range(gen):
        nxt = int(np.argmax(out[-1]))
        lo, st = decode_step(params, st, jnp.asarray([nxt], jnp.int32), mc)
        out.append(np.asarray(lo[0]))
    return out


@pytest.mark.parametrize("name", ["mpt-wpe", "mpt-alibi", "llama-gqa"])
@pytest.mark.parametrize("chunk_cap", [4, 6, 100])
def test_mixed_step_bitexact_with_contiguous(name, chunk_cap):
    """The acceptance pin: chunked prefill (several chunk budgets,
    including the one-shot 100 case) + decode through the GATHER path ==
    the contiguous oracle, every emission, bitwise."""
    from photon_tpu.models.mpt import init_params

    cfg = _serve_cfg(alibi=name == "mpt-alibi", llama=name == "llama-gqa")
    params = init_params(cfg.model, seed=4)
    rng = np.random.default_rng(1)
    prompt = list(map(int, rng.integers(1, cfg.model.vocab_size, 9)))
    got, _ = _drive_chunked(cfg, params, prompt, chunk_cap, gen=5)
    want = _oracle_logits(cfg, params, prompt, gen=5)
    for i, (a, b) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(a, b, err_msg=f"emission {i}")


@pytest.mark.parametrize("name", ["mpt-wpe", "mpt-alibi", "llama-gqa"])
def test_ragged_kernel_epsilon_vs_contiguous(name):
    """The fused kernel drives the same chunk/decode stream; every
    emission stays within the pinned epsilon of the contiguous oracle."""
    from photon_tpu.models.mpt import init_params

    cfg = _serve_cfg(alibi=name == "mpt-alibi", llama=name == "llama-gqa")
    params = init_params(cfg.model, seed=4)
    rng = np.random.default_rng(2)
    prompt = list(map(int, rng.integers(1, cfg.model.vocab_size, 9)))
    got, _ = _drive_chunked(cfg, params, prompt, 4, gen=4, impl="ragged")
    want = _oracle_logits(cfg, params, prompt, gen=4)
    for i, (a, b) in enumerate(zip(got, want)):
        assert _rel(a, b) < RAGGED_KERNEL_EPS, f"emission {i}"


@pytest.mark.parametrize("name", ["mpt-wpe", "llama-gqa"])
def test_engine_chunked_matches_offline_after_recycling(name):
    """Engine-level acceptance across block recycling: admissions run
    through the chunked path (budget 3 forces multi-chunk prefills on a
    LIFO-recycled pool) and every completion equals the offline oracle —
    including requests admitted into blocks a previous request just
    freed, and a prefix-cache-hit admission."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(llama=name == "llama-gqa", prefix=True, budget=3)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(engine, max_queue=16,
                                prefill_token_budget=3).start()
    rng = np.random.default_rng(5)
    shared = list(map(int, rng.integers(1, cfg.model.vocab_size, 8)))
    try:
        for i in range(6):
            suf = list(map(int, rng.integers(1, cfg.model.vocab_size,
                                             int(rng.integers(1, 6)))))
            p = (shared + suf) if i % 2 else suf
            got = batcher.submit(p, 4).result(timeout=120)
            assert got == _offline_greedy(cfg, params, p, 4), p
        assert engine.prefix_cache.tokens_cached > 0  # hits happened
        assert batcher.chunk_split_prompts > 0  # prompts really split
        assert engine.n_active == 0
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# 3. decode cadence under a 4x-budget prompt
# ---------------------------------------------------------------------------


def test_decode_cadence_survives_giant_prompt():
    """Regression for the PR 5 carve-out: with chunked prefill, an
    in-flight decode emits a token on EVERY step of a 4x-budget prompt's
    admission — the giant prompt pays its prefill across chunks instead
    of stalling the decode for the whole thing. Driven synchronously
    (batcher not started: this test owns the driver phases)."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    budget = 4
    cfg = _serve_cfg(n_slots=2, max_seq=64, max_new=32, budget=budget)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(engine, max_queue=4,
                                prefill_token_budget=budget)
    rng = np.random.default_rng(9)
    decode_req = batcher.submit([5, 9, 2], 24)
    giant = list(map(int, rng.integers(1, cfg.model.vocab_size, 4 * budget)))
    batcher._admit_phase()
    # get the decode request past its own (short) prefill first
    while engine.pending_tokens(0) > 0:
        batcher._step_phase()
    big_req = batcher.submit(giant, 4)
    batcher._admit_phase()
    big_slot = next(s for s, r in batcher._running.items() if r is big_req)
    assert engine.pending_tokens(big_slot) == len(giant)
    chunk_steps = 0
    while engine.pending_tokens(big_slot) > 0:
        before = len(decode_req.generated)
        batcher._step_phase()
        chunk_steps += 1
        # THE pin: the decode row advanced on this very chunk step
        assert len(decode_req.generated) == before + 1, (
            f"decode stalled during chunk step {chunk_steps}"
        )
    assert chunk_steps == 4  # 4x budget → exactly 4 chunk steps
    assert batcher.chunk_split_prompts == 1
    # drain cleanly: finish both requests, then verify against the oracle
    while not (decode_req.finished and big_req.finished):
        batcher._step_phase()
    assert decode_req.generated == _offline_greedy(cfg, params, [5, 9, 2], 24)
    assert big_req.generated == _offline_greedy(cfg, params, giant, 4)
    batcher.close()


# ---------------------------------------------------------------------------
# 4. config gating
# ---------------------------------------------------------------------------


def test_attention_impl_validation():
    cfg = _serve_cfg()
    cfg.photon.serve.attention_impl = "fused"  # unknown impl
    with pytest.raises(ValueError, match="attention_impl"):
        cfg.validate()
    # explicit ragged on this (CPU) backend without interpret: validation
    # failure, not a runtime one
    cfg.photon.serve.attention_impl = "ragged"
    cfg.photon.serve.attention_interpret = False
    with pytest.raises(ValueError, match="Pallas-capable"):
        cfg.validate()
    cfg.photon.serve.attention_interpret = True  # interpreter opt-in passes
    cfg.validate()
    cfg.photon.serve.attention_impl = "gather"
    cfg.photon.serve.attention_interpret = False
    cfg.validate()
    cfg.photon.serve.attention_impl = "auto"
    cfg.validate()


def test_engine_impl_resolution():
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine

    params_cfg = _serve_cfg(attn="gather")
    params = init_params(params_cfg.model, seed=0)
    g = PagedEngine(params_cfg, params)
    assert g.attn_impl == "gather" and g.attn_stats()["ragged"] == 0.0
    a = PagedEngine(_serve_cfg(attn="auto"), params)
    # CPU sandbox: auto = the ragged walk with the gather-reference math
    assert a.attn_impl == "ragged-ref" and a.attn_stats()["ragged"] == 1.0
    r = PagedEngine(_serve_cfg(attn="ragged", interpret=True), params)
    assert r.attn_impl == "ragged"


def test_gather_impl_serves_full_width():
    """attention_impl=gather keeps the PR 5 cost model (full-width walk)
    and still matches the offline oracle (it IS the oracle path)."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(attn="gather")
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    assert engine.attn_stats()["ctx_blocks"] == engine.max_blocks
    batcher = ContinuousBatcher(engine, max_queue=4).start()
    try:
        got = batcher.submit([5, 9, 2, 7], 5).result(timeout=120)
        assert got == _offline_greedy(cfg, params, [5, 9, 2, 7], 5)
    finally:
        batcher.close()


def test_ragged_kernel_engine_matches_offline():
    """The fused kernel as the ENGINE's inner loop (interpret mode):
    greedy completions equal the offline oracle — the epsilon tier is far
    inside the argmax margin on this model."""
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(attn="ragged", interpret=True)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(engine, max_queue=4).start()
    try:
        for p in ([5, 9, 2, 7], [3, 3, 8, 1, 4, 4]):
            got = batcher.submit(p, 4).result(timeout=180)
            assert got == _offline_greedy(cfg, params, p, 4), p
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# 5. the retrace sentinel across chunked ragged bursts + hits + a swap
# ---------------------------------------------------------------------------


def test_retrace_sentinel_green_chunked_with_hits_and_swap():
    """The ISSUE 12 sentinel pin: with every (chunk-width, live-width)
    bucket warm, a ragged burst of SPLIT prompts (budget 4 → multi-chunk
    prefills) mixed with decode rows, prefix-cache hits AND one live
    hot-swap compiles NOTHING. Fixed length profile so every burst
    exercises the same buckets; the live width is a monotone high-water,
    so admission timing can't mint fresh shapes."""
    from photon_tpu.analysis import runtime as lint_rt
    from photon_tpu.models.mpt import init_params
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _serve_cfg(n_slots=2, max_seq=32, prefix=True, budget=4)
    params = init_params(cfg.model, seed=4)
    engine = PagedEngine(cfg, params)
    batcher = ContinuousBatcher(engine, max_queue=32,
                                prefill_token_budget=4).start()
    rng = np.random.default_rng(17)
    shared = list(map(int, rng.integers(1, cfg.model.vocab_size, 8)))
    profile = [(1, 2), (6, 3), (3, 4), (4, 2), (5, 3), (2, 2)]

    def burst():
        reqs = []
        for i, (suf_len, max_new) in enumerate(profile):
            suf = list(map(int, rng.integers(1, cfg.model.vocab_size, suf_len)))
            reqs.append(batcher.submit(
                (shared + suf) if i % 2 else suf, max_new
            ))
        for r in reqs:
            r.result(timeout=180)

    try:
        burst()  # warm 1: misses populate the cache; hws rise to final
        done = batcher.request_swap(dict(params), loaded_round=1)
        assert done.wait(60)
        burst()  # warm 2: every final-width bucket incl. hit suffixes
        with lint_rt.retrace_guard(steady=True) as sentinel:
            burst()
            done = batcher.request_swap(dict(params), loaded_round=2)
            assert done.wait(60)
            burst()
        assert sentinel.violations == []
        assert batcher.chunk_split_prompts > 0  # chunking genuinely happened
        assert engine.loaded_round == 2
    finally:
        batcher.close()
