"""Asynchronous federated rounds (ISSUE 18).

Contract layers:

1. **The bit-parity pin**: with homogeneous client speed and
   ``K == n_total_clients`` the async runner IS the synchronous runner —
   bit-for-bit identical parameters and optimizer state after N
   versions/rounds, for all five server optimizers, fp32 AND q8, fused
   device plane AND host path. This is the transitive oracle: every
   correctness property the sync suite proves transfers to the async
   zero-staleness corner for free.
2. staleness-discount weight math (poly/const, dtype signature switch);
3. the robustness ladder reframed on the version clock: max-staleness
   reject → fresh-version re-broadcast, min-arrivals stall (never an
   aborted run), liveness edge drops the in-flight delta, SIGKILL-mid-fit
   drops cleanly while the clock keeps advancing;
4. chaos determinism: the seeded per-client ``fit_delay_plan``;
5. the acceptance e2e: SIGKILL one client + 4x-slow another mid-stream →
   survivors advance the clock unaffected, and the PR 10 hot-swap watcher
   consumes a streamed version mid-traffic with zero dropped requests.
"""

import threading

import numpy as np
import pytest

from photon_tpu import chaos, telemetry
from photon_tpu.config.schema import Config, TelemetryConfig
from photon_tpu.federation.async_round import AsyncFedRunner
from photon_tpu.federation.collective_round import CollectiveFedRunner


@pytest.fixture(autouse=True)
def _clean_planes():
    yield
    chaos.uninstall()
    telemetry.uninstall()


def _cfg(tmp_path, strategy="fedavg", n_clients=2, quantization="off",
         device_opt=True, n_rounds=3, K=0, min_arrivals=1, max_staleness=4,
         power=1.0) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 4
    cfg.train.device_microbatch_size = 4
    cfg.fl.n_total_clients = n_clients
    cfg.fl.n_clients_per_round = n_clients
    cfg.fl.n_rounds = n_rounds
    cfg.fl.local_steps = 2
    cfg.fl.eval_interval_rounds = 0
    cfg.fl.strategy_name = strategy
    cfg.fl.server_learning_rate = 1.0 if strategy == "fedavg" else 0.01
    if strategy in ("fedadam", "fedyogi"):
        cfg.fl.server_tau = 1e-3
    cfg.dataset.synthetic = True
    cfg.photon.checkpoint = False
    cfg.photon.comm_stack.collective = True
    cfg.photon.comm_stack.shm = False
    cfg.photon.comm_stack.collective_replica = 2
    cfg.photon.comm_stack.collective_quantization = quantization
    cfg.photon.comm_stack.collective_q8_block = 64
    cfg.photon.comm_stack.collective_device_optimizer = device_opt
    cfg.photon.save_path = str(tmp_path / "run")
    cfg.run_uuid = "async-round"
    return cfg


def _async_cfg(tmp_path, **kw) -> Config:
    cfg = _cfg(tmp_path, **kw)
    ar = cfg.photon.async_rounds
    ar.enabled = True
    ar.buffer_size = kw.get("K", 0)
    ar.min_arrivals = kw.get("min_arrivals", 1)
    ar.max_staleness = kw.get("max_staleness", 4)
    ar.staleness_power = kw.get("power", 1.0)
    cfg.validate()
    return cfg


# ---------------------------------------------------------------------------
# 1. the bit-parity pin: zero staleness + K = cohort == the synchronous round
# ---------------------------------------------------------------------------


def _assert_bit_identical(a: AsyncFedRunner, s: CollectiveFedRunner):
    assert a.server_steps_cumulative == s.server_steps_cumulative
    for pa, ps in zip(a.strategy.current_parameters,
                      s.strategy.current_parameters):
        assert np.array_equal(pa, ps)
    sa, ss = a.state_for_checkpoint(), s.state_for_checkpoint()
    assert set(sa) == set(ss)
    for k in sa:
        for xa, xs in zip(sa[k], ss[k]):
            assert np.array_equal(xa, xs), k


@pytest.mark.parametrize(
    "strategy,quantization",
    [
        ("fedavg", "off"),
        ("fedadam", "q8"),
        pytest.param("fedavg", "q8", marks=pytest.mark.slow),
        pytest.param("nesterov", "off", marks=pytest.mark.slow),
        pytest.param("nesterov", "q8", marks=pytest.mark.slow),
        pytest.param("fedmom", "off", marks=pytest.mark.slow),
        pytest.param("fedmom", "q8", marks=pytest.mark.slow),
        pytest.param("fedadam", "off", marks=pytest.mark.slow),
        pytest.param("fedyogi", "off", marks=pytest.mark.slow),
        pytest.param("fedyogi", "q8", marks=pytest.mark.slow),
    ],
)
def test_zero_staleness_is_bitexact_sync(tmp_path, strategy, quantization):
    """K = cohort + homogeneous speed: every buffer is the full cohort at
    staleness 0, the int32 weight signature reuses the compiled sync
    program, and N async versions == N sync rounds bit-for-bit — params
    AND optimizer state, through the fused ZeRO-1 device plane."""
    sync_cfg = _cfg(tmp_path / "sync", strategy=strategy,
                    quantization=quantization)
    sync_cfg.validate()
    sync = CollectiveFedRunner(sync_cfg, [0, 1])
    for r in (1, 2, 3):
        sync.run_round(r)

    acfg = _async_cfg(tmp_path / "async", strategy=strategy,
                      quantization=quantization)
    runner = AsyncFedRunner(acfg, [0, 1])
    runner.run_versions(3, eval_every=0)

    assert runner.version == 3
    assert all(runner.aggregation_paths[v] == "async" for v in (1, 2, 3))
    _assert_bit_identical(runner, sync)
    # the parity fold rode the sync program: int32 weights, no discounts
    assert runner.history.latest("server/async_staleness_max") == 0.0
    assert runner.history.latest("server/async_discount_mean") == 1.0


def test_zero_staleness_bitexact_host_path(tmp_path):
    """Same pin with the device optimizer off: the async fold lands in
    ``_apply_average_host`` exactly like the sync host path."""
    sync_cfg = _cfg(tmp_path / "sync", device_opt=False)
    sync_cfg.validate()
    sync = CollectiveFedRunner(sync_cfg, [0, 1])
    for r in (1, 2, 3):
        sync.run_round(r)

    runner = AsyncFedRunner(_async_cfg(tmp_path / "async", device_opt=False),
                            [0, 1])
    runner.run_versions(3, eval_every=0)
    _assert_bit_identical(runner, sync)
    # N_SAMPLES stayed the sync path's integer total
    assert runner.history.latest("server/n_samples") \
        == sync.history.latest("server/n_samples")


def test_async_steady_state_is_compile_free(tmp_path):
    """Every fold zero-pads to the one full-mesh program: versions 2+ run
    the version-1 executables (PR 6 retrace discipline on the new loop)."""
    from photon_tpu.analysis.runtime import (
        install_retrace_sentinel,
        uninstall_retrace_sentinel,
    )

    cfg = _async_cfg(tmp_path, strategy="fedadam", n_rounds=4)
    sentinel = install_retrace_sentinel()
    try:
        runner = AsyncFedRunner(cfg, [0, 1])
        sentinel.mark_steady_after(1)  # version 1 = fit + fold compiles
        runner.run_versions(4, eval_every=0)
        sentinel.check("async/steady-state")
    finally:
        uninstall_retrace_sentinel()
    assert runner.version == 4


# ---------------------------------------------------------------------------
# 2. staleness-discount weight math
# ---------------------------------------------------------------------------


def test_staleness_discount_policies():
    from photon_tpu.parallel.collective_agg import staleness_discount

    np.testing.assert_allclose(
        staleness_discount([0, 1, 3], "poly", 1.0), [1.0, 0.5, 0.25]
    )
    np.testing.assert_allclose(
        staleness_discount([0, 1, 3], "poly", 2.0), [1.0, 0.25, 0.0625]
    )
    np.testing.assert_allclose(
        staleness_discount([0, 5, 9], "const"), [1.0, 1.0, 1.0]
    )
    with pytest.raises(ValueError, match="staleness"):
        staleness_discount([-1], "poly")
    with pytest.raises(ValueError):
        staleness_discount([0], "exp")


def test_discounted_fold_weights_dtype_signature():
    """All-fresh buffers come back int32 — the EXACT input signature of
    the compiled sync program (the parity mechanism); any real discount
    switches to float32 sample-weight products."""
    from photon_tpu.parallel.collective_agg import discounted_fold_weights

    w = discounted_fold_weights([10, 20], [0, 0])
    assert w.dtype == np.int32 and list(w) == [10, 20]
    w = discounted_fold_weights([10, 20], [0, 1], "poly", 1.0)
    assert w.dtype == np.float32
    np.testing.assert_allclose(w, [10.0, 10.0])
    # const policy never discounts — int32 at ANY staleness
    w = discounted_fold_weights([10, 20], [0, 7], "const")
    assert w.dtype == np.int32


# ---------------------------------------------------------------------------
# 3. the robustness ladder on the version clock
# ---------------------------------------------------------------------------


def test_max_staleness_reject_rebroadcasts_fresh_version(tmp_path):
    """K=1 + a pinned 4x-slow client: the fast client advances the clock;
    the slow delta lands 3 versions stale > max_staleness=0, is rejected
    (counted, evented) and the client re-dispatched from the CURRENT
    version — its next delta is fresh."""
    events_path = tmp_path / "events.jsonl"
    telemetry.install(TelemetryConfig(enabled=True), scope="server",
                      events_path=str(events_path))
    cfg = _async_cfg(tmp_path, K=1, max_staleness=0, n_rounds=5)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.fit_delay_factor = 4.0
    cfg.photon.chaos.fit_delay_cid = 1
    cfg.validate()
    chaos.install(cfg.photon.chaos, scope="collective0")
    runner = AsyncFedRunner(cfg, [0, 1])
    runner.run_versions(5, eval_every=0)

    assert runner.version == 5
    assert runner.rejected_total == 1
    assert runner.history.latest("server/async_rejected_total") == 1.0
    telemetry.uninstall()
    events = telemetry.read_events_jsonl(str(events_path))
    rejects = [e for e in events if e["kind"] == "async/stale_reject"]
    assert len(rejects) == 1
    assert rejects[0]["attrs"]["cid"] == 1
    assert rejects[0]["attrs"]["staleness"] == 3
    assert any(e["kind"] == "chaos/fit_delay" for e in events)
    assert any(e["kind"] == "async/version_advance" for e in events)


def test_min_arrivals_stall_holds_clock_never_aborts(tmp_path):
    """One client SIGKILLed at its first fit leaves a single contributor:
    the buffer fills (same cid twice) but min_arrivals=2 holds the version
    clock — stall counted + evented, the run RETURNS (no exception, no
    abort) at version 0."""
    events_path = tmp_path / "events.jsonl"
    telemetry.install(TelemetryConfig(enabled=True), scope="server",
                      events_path=str(events_path))
    cfg = _async_cfg(tmp_path, K=2, min_arrivals=2, n_rounds=2)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 1
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.validate()

    def _client_crash(code):
        raise RuntimeError(f"simulated SIGKILL ({code})")

    chaos.install(cfg.photon.chaos, scope="collective0",
                  crash_fn=_client_crash)
    runner = AsyncFedRunner(cfg, [0, 1])
    with pytest.warns(UserWarning):
        hist = runner.run_versions(2, eval_every=0)

    assert runner.version == 0  # the clock held — never advanced undiverse
    assert runner.stalls_total >= 1
    assert runner.dropped_total == 1  # the SIGKILLed fit's delta
    assert hist is runner.history  # returned, not raised
    telemetry.uninstall()
    kinds = [e["kind"]
             for e in telemetry.read_events_jsonl(str(events_path))]
    assert "async/min_arrivals_stall" in kinds
    assert "async/delta_dropped" in kinds


def test_liveness_edge_drops_inflight_delta(tmp_path):
    """A delta in flight when its client goes dead is dropped at delivery:
    evented, counted, never buffered, client not re-dispatched."""
    cfg = _async_cfg(tmp_path, K=2)
    runner = AsyncFedRunner(cfg, [0, 1])
    assert runner._dispatch(0) and runner._dispatch(1)
    # the liveness plane marks client1 dead while its delta is in flight
    runner.liveness.observe_miss("client1")
    runner.liveness.observe_miss("client1")
    survivors = [
        cid for cid, arrays, n, base in runner._pop_burst()
        if runner._admit(cid, arrays, n, base)
    ]
    assert survivors == [0]
    assert runner.dropped_total == 1
    assert [e.cid for e in runner.buffer] == [0]


def test_sigkill_mid_fit_drops_cleanly_clock_advances(tmp_path):
    """SIGKILL (chaos mid-fit, one-shot marker) under the async loop: the
    killed client's would-be delta is dropped cleanly, survivors keep the
    version clock advancing to target, params stay finite."""
    cfg = _async_cfg(tmp_path, n_clients=3, K=2, n_rounds=4)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 2  # first re-dispatch after version 1
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.validate()

    def _client_crash(code):
        raise RuntimeError(f"simulated SIGKILL ({code})")

    inj = chaos.install(cfg.photon.chaos, scope="collective0",
                        crash_fn=_client_crash)
    runner = AsyncFedRunner(cfg, [0, 1, 2])
    with pytest.warns(UserWarning, match="delta is dropped"):
        runner.run_versions(4, eval_every=0)

    assert inj.counts["crash"] == 1
    assert runner.version == 4
    assert runner.dropped_total == 1
    for p in runner.strategy.current_parameters:
        assert np.all(np.isfinite(p))
    # the async clock rode into the checkpointed control state
    control = runner.control_state_for_checkpoint()
    assert control["async_version"] == 4
    assert control["async_dropped_total"] == 1


def test_grouped_burst_matches_sequential_folds(tmp_path):
    """B complete buffers landing in one burst on the host path fold
    through ONE grouped-SPMD program; the result matches B sequential
    single-buffer folds on an identically-seeded runner."""
    cfg = _async_cfg(tmp_path / "a", K=1, device_opt=False)
    ra = AsyncFedRunner(cfg, [0, 1])
    rb = AsyncFedRunner(
        _async_cfg(tmp_path / "b", K=1, device_opt=False), [0, 1]
    )
    for p, q in zip(ra.strategy.current_parameters,
                    rb.strategy.current_parameters):
        assert np.array_equal(p, q)  # same seed → same init
    assert ra._dispatch(0) and ra._dispatch(1)
    burst = ra._pop_burst()
    for cid, arrays, n, base in burst:
        assert ra._admit(cid, arrays, n, base)
    buffers = [[ra.buffer[0]], [ra.buffer[1]]]
    ra.buffer = []
    ra._fold_grouped(buffers)
    rb._fold_one([buffers[0][0]])
    rb._fold_one([buffers[1][0]])
    assert ra.version == rb.version == 2
    for p, q in zip(ra.strategy.current_parameters,
                    rb.strategy.current_parameters):
        np.testing.assert_allclose(p, q, rtol=1e-6, atol=1e-7)


def test_fold_failure_rolls_back_and_continues(tmp_path, monkeypatch):
    """A fold that raises mid-update restores the per-version snapshot:
    params/state/step-counter exactly at the pre-fold version, clock held,
    loop continues (never an aborted run)."""
    cfg = _async_cfg(tmp_path, device_opt=False)
    runner = AsyncFedRunner(cfg, [0, 1])
    assert runner._dispatch(0) and runner._dispatch(1)
    for cid, arrays, n, base in runner._pop_burst():
        runner._admit(cid, arrays, n, base)
    before = [p.copy() for p in runner.strategy.current_parameters]

    def _boom(*a, **k):
        raise RuntimeError("torn fold")

    monkeypatch.setattr(runner.strategy, "apply_average", _boom)
    entries = runner.buffer[:runner.K]
    del runner.buffer[:runner.K]
    with pytest.warns(UserWarning, match="rolled back"):
        runner._fold_one(entries)
    assert runner.version == 0
    assert runner.folds_failed_total == 1
    for p, q in zip(before, runner.strategy.current_parameters):
        assert np.array_equal(p, q)
    assert runner.history.latest("server/round_failed") == 1.0


# ---------------------------------------------------------------------------
# 4. chaos: deterministic per-client fit delay
# ---------------------------------------------------------------------------


def test_fit_delay_plan_deterministic_and_scoped(tmp_path):
    from photon_tpu.chaos.injector import FaultInjector, validate_chaos_config

    cfg = Config().photon.chaos
    cfg.enabled = True
    cfg.fit_delay_factor = 4.0
    validate_chaos_config(cfg)
    a = FaultInjector(cfg, scope="nodeA")
    # pure function of (seed, scope, cid): stable across calls + injectors
    f0, f1 = a.fit_delay_plan(0), a.fit_delay_plan(1)
    assert a.fit_delay_plan(0) == f0 and a.fit_delay_plan(1) == f1
    assert FaultInjector(cfg, scope="nodeA").fit_delay_plan(0) == f0
    assert 1.0 <= f0 < 4.0 and 1.0 <= f1 < 4.0
    assert f0 != f1  # seeded per-client draw, not one global slowdown
    assert FaultInjector(cfg, scope="nodeB").fit_delay_plan(0) != f0
    assert a.counts["fit_delay"] >= 2

    # pinned cid: exact ceiling on that client, no-op on every other
    cfg.fit_delay_cid = 1
    b = FaultInjector(cfg, scope="nodeA")
    assert b.fit_delay_plan(1) == 4.0
    assert b.fit_delay_plan(0) == 1.0

    # off (factor 0) and identity (factor 1) never fire the hook
    cfg.fit_delay_factor = 0.0
    assert FaultInjector(cfg, scope="x").fit_delay_plan(3) == 1.0
    cfg.fit_delay_factor = 0.5
    with pytest.raises(ValueError, match="fit_delay_factor"):
        validate_chaos_config(cfg)


def test_fit_delay_rides_fit_metrics(tmp_path):
    """The injector's factor lands in FitRes metrics — the wire the async
    DES clock reads its per-client duration from."""
    cfg = _async_cfg(tmp_path, K=1, n_rounds=1)
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.fit_delay_factor = 4.0
    cfg.photon.chaos.fit_delay_cid = 1
    cfg.validate()
    chaos.install(cfg.photon.chaos, scope="collective0")
    runner = AsyncFedRunner(cfg, [0, 1])
    assert runner._dispatch(0) and runner._dispatch(1)
    times = {runner._inflight[seq][0]: t for t, seq in runner._heap}
    assert times[1] == pytest.approx(4.0 * times[0])


# ---------------------------------------------------------------------------
# 5. config plumbing
# ---------------------------------------------------------------------------


def test_async_config_validation(tmp_path):
    cfg = _cfg(tmp_path)
    cfg.photon.async_rounds.enabled = True
    cfg.photon.comm_stack.collective = False
    cfg.photon.comm_stack.shm = True
    with pytest.raises(ValueError, match="collective"):
        cfg.validate()

    cfg = _cfg(tmp_path)
    cfg.photon.async_rounds.enabled = True
    cfg.photon.async_rounds.staleness_policy = "exp"
    with pytest.raises(ValueError, match="staleness_policy"):
        cfg.validate()

    cfg = _cfg(tmp_path)
    cfg.photon.async_rounds.enabled = True
    cfg.photon.async_rounds.buffer_size = 1
    cfg.photon.async_rounds.min_arrivals = 2
    with pytest.raises(ValueError, match="min_arrivals"):
        cfg.validate()

    cfg = _cfg(tmp_path)
    cfg.photon.async_rounds.buffer_size = 3  # knobs set but enabled=False
    with pytest.warns(UserWarning, match="async_rounds"):
        cfg.validate()


def test_async_runner_requires_enabled(tmp_path):
    cfg = _cfg(tmp_path)
    cfg.validate()
    with pytest.raises(ValueError, match="async_rounds.enabled"):
        AsyncFedRunner(cfg, [0, 1])


# ---------------------------------------------------------------------------
# 6. the acceptance e2e: chaos mid-stream + hot-swap mid-traffic
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_stream_hotswap_consumes_versions_mid_traffic(tmp_path):
    """SIGKILL one client (chaos mid-fit) AND 4x-slow another mid-stream:
    the version clock keeps advancing on survivors, every advance streams
    a version-tagged checkpoint, and a live serving plane (PagedEngine +
    ContinuousBatcher + CheckpointWatcher) swaps to streamed versions
    mid-traffic with ZERO dropped requests."""
    from photon_tpu.checkpoint import FileStore
    from photon_tpu.checkpoint.server import ServerCheckpointManager
    from photon_tpu.serve.engine import PagedEngine
    from photon_tpu.serve.hotswap import CheckpointWatcher
    from photon_tpu.serve.scheduler import ContinuousBatcher

    cfg = _async_cfg(tmp_path, n_clients=3, K=2, n_rounds=3)
    cfg.photon.serve.n_slots = 2
    cfg.photon.serve.block_size = 4
    cfg.photon.serve.max_new_tokens = 4
    cfg.photon.chaos.enabled = True
    cfg.photon.chaos.crash_phase = "mid-fit"
    cfg.photon.chaos.crash_round = 2
    cfg.photon.chaos.crash_marker = str(tmp_path / "crash.marker")
    cfg.photon.chaos.fit_delay_factor = 4.0
    cfg.photon.chaos.fit_delay_cid = 2
    cfg.validate()

    def _client_crash(code):
        raise RuntimeError(f"simulated SIGKILL ({code})")

    chaos.install(cfg.photon.chaos, scope="collective0",
                  crash_fn=_client_crash)
    store = FileStore(tmp_path / "store")
    mgr = ServerCheckpointManager(store, cfg.run_uuid)

    runner = AsyncFedRunner(cfg, [0, 1, 2])
    runner.save_checkpoint(mgr, 0)  # the round the engine boots from
    engine = PagedEngine.from_checkpoint(cfg, store=store, resume_round=-1)
    batcher = ContinuousBatcher(engine, max_queue=16).start()
    watcher = CheckpointWatcher(batcher, mgr, cfg, poll_s=0.01)
    assert engine.loaded_round == 0

    err: list[BaseException] = []

    def _train():
        try:
            with pytest.warns(UserWarning):
                runner.run_versions(3, ckpt_mgr=mgr, ckpt_every=1,
                                    eval_every=0)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            err.append(e)

    t = threading.Thread(target=_train)
    t.start()
    futures = []
    try:
        import time as _time

        from photon_tpu.serve.scheduler import (
            DrainingError,
            QueueFullError,
        )

        while t.is_alive():
            try:
                # drain-window/full-queue rejections are the admission
                # plane's 429/503 — an ACCEPTED request must never drop
                futures.append(batcher.submit([5, 9, 2], 4))
            except (DrainingError, QueueFullError):
                pass
            watcher.poll_once()
            _time.sleep(0.02)
        t.join()
        # drain the tail: the final streamed version must be consumable
        deadline = 100
        while engine.loaded_round < 3 and deadline:
            watcher.poll_once()
            _time.sleep(0.02)
            deadline -= 1
        futures.append(batcher.submit([5, 9, 2], 4))
        # ZERO dropped: every request admitted across the swaps completes
        assert futures
        for f in futures:
            out = f.result(timeout=120)
            assert len(out) == 4
    finally:
        batcher.close()
    assert not err, err
    assert runner.version == 3  # survivors advanced the clock to target
    assert runner.dropped_total == 1  # the SIGKILLed fit
    assert engine.loaded_round == 3 and batcher.swaps >= 1
    assert watcher.swaps_applied >= 1
    # the streamed manifests carry the async clock in server_state
    _, _, _, server_state = mgr.load_round(3)
    assert server_state["async_version"] == 3
