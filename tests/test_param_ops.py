"""param_ops tests: momenta payloads, personalization/randomization,
embedding transplant, parameters_checker; plus trainer momenta round-trip,
freezing, and a momenta-aggregating fed round."""

import numpy as np
import pytest

from photon_tpu.codec import ParamsMetadata
from photon_tpu.train.param_ops import (
    extend_with_momenta,
    has_momenta,
    parameters_checker,
    personalize_layers,
    randomize_layers,
    split_momenta,
    transplant_embeddings,
)


def _payload(n=3, seed=0):
    rng = np.random.default_rng(seed)
    arrays = [rng.normal(size=(4, 2)).astype(np.float32) for _ in range(n)]
    names = ["blocks/block/ln_1/scale", "blocks/block/wqkv/kernel", "wte/embedding"][:n]
    return ParamsMetadata.from_ndarrays(names, arrays), arrays


def test_momenta_roundtrip():
    meta, params = _payload()
    m1 = [np.full_like(p, 1.0) for p in params]
    m2 = [np.full_like(p, 2.0) for p in params]
    ext_meta, ext = extend_with_momenta(meta, params, m1, m2)
    assert has_momenta(ext_meta) and not has_momenta(meta)
    assert len(ext) == 9
    base, p2, m1b, m2b = split_momenta(ext_meta, ext)
    assert base.names == meta.names
    np.testing.assert_array_equal(m1b[0], m1[0])
    np.testing.assert_array_equal(m2b[2], m2[2])


def test_momenta_zero_init():
    meta, params = _payload()
    _, ext = extend_with_momenta(meta, params)
    assert all(np.all(a == 0) for a in ext[3:])


def test_personalize_and_randomize():
    meta, incoming = _payload(seed=1)
    local = [a + 100 for a in incoming]
    out = personalize_layers(meta, incoming, local, [r"wqkv"])
    np.testing.assert_array_equal(out[1], local[1])
    np.testing.assert_array_equal(out[0], incoming[0])

    r1 = randomize_layers(meta, incoming, [r"wqkv"], seed=7)
    r2 = randomize_layers(meta, incoming, [r"wqkv"], seed=7)
    np.testing.assert_array_equal(r1[1], r2[1])  # deterministic
    assert not np.allclose(r1[1], incoming[1])
    np.testing.assert_array_equal(r1[0], incoming[0])  # untouched


def test_transplant_embeddings():
    meta, arrays = _payload()
    donor_meta, donor = _payload(seed=9)
    out = transplant_embeddings(meta, arrays, donor_meta, donor)
    np.testing.assert_array_equal(out[2], donor[2])
    np.testing.assert_array_equal(out[1], arrays[1])


def test_parameters_checker():
    _, a = _payload()
    b = [x.copy() for x in a]
    parameters_checker(a, b, expect_equal=True)
    with pytest.raises(ValueError):
        parameters_checker(a, b, expect_equal=False)
    b[0] = b[0] + 1
    parameters_checker(a, b, expect_equal=False)
    with pytest.raises(ValueError):
        parameters_checker(a, b, expect_equal=True)


def test_trainer_momenta_roundtrip(tiny_trainer):
    trainer, batch = tiny_trainer
    trainer.fit([batch] * 3, duration_steps=3)
    m1, m2 = trainer.get_momenta()
    assert any(np.any(m != 0) for m in m1)
    new_m1 = [np.full_like(m, 0.5) for m in m1]
    new_m2 = [np.full_like(m, 0.25) for m in m2]
    trainer.set_momenta(new_m1, new_m2)
    got_m1, got_m2 = trainer.get_momenta()
    np.testing.assert_allclose(got_m1[0], new_m1[0])
    np.testing.assert_allclose(got_m2[0], new_m2[0])


def test_freeze_patterns():
    import jax
    from photon_tpu.config.schema import (
        Config, MeshConfig, ModelConfig, OptimizerConfig, SchedulerConfig, TrainConfig,
    )
    from photon_tpu.train.trainer import Trainer

    cfg = Config(
        model=ModelConfig(d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
                          attn_impl="xla", compute_dtype="float32"),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-2, freeze_patterns=[r"wte/embedding"]),
        scheduler=SchedulerConfig(t_warmup=1, t_max=50),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4),
    )
    trainer = Trainer(cfg, init_seed=0)
    before_meta, before = trainer.get_parameters()
    batch = np.random.default_rng(0).integers(0, 64, (4, 16), dtype=np.int64)
    trainer.fit([batch] * 3, duration_steps=3)
    _, after = trainer.get_parameters()
    for name, b, a in zip(before_meta.names, before, after):
        if "wte/embedding" in name:
            np.testing.assert_array_equal(b, a)  # frozen
        elif "wqkv" in name:
            assert not np.allclose(b, a)  # trained


def test_momenta_payload_survives_npz_and_objstore(tmp_path):
    """Regression: npz round-trips must preserve [params|m1|m2] ORDER —
    alphabetical npz key iteration would put '__momenta__' names first."""
    from photon_tpu.checkpoint import FileStore, arrays_to_npz, npz_to_arrays
    from photon_tpu.federation.transport import ParamTransport

    meta, params = _payload()
    ext_meta, ext = extend_with_momenta(meta, params)
    m2, a2 = npz_to_arrays(arrays_to_npz(ext_meta, ext))
    assert m2.names == ext_meta.names  # exact order, momenta last
    base, _, _, _ = split_momenta(m2, a2)
    assert base.names == meta.names

    tr = ParamTransport("objstore", store=FileStore(tmp_path / "s"))
    ptr = tr.put("momenta-payload", ext_meta, ext)
    got_meta, got = tr.get(ptr)
    assert got_meta.names == ext_meta.names
    split_momenta(got_meta, got)  # must not raise


def test_momenta_with_frozen_params():
    """Regression: freeze_patterns leaves MaskedNode (no state) at frozen
    slots; get/set_momenta must still align with the full param list."""
    import numpy as np
    from photon_tpu.config.schema import (
        Config, MeshConfig, ModelConfig, OptimizerConfig, SchedulerConfig, TrainConfig,
    )
    from photon_tpu.train.trainer import Trainer

    cfg = Config(
        model=ModelConfig(d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
                          attn_impl="xla", compute_dtype="float32"),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3, freeze_patterns=[r"wte/embedding"]),
        scheduler=SchedulerConfig(t_warmup=1, t_max=50),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4),
    )
    trainer = Trainer(cfg, init_seed=0)
    batch = np.random.default_rng(0).integers(0, 64, (4, 16), dtype=np.int64)
    trainer.fit([batch] * 2, duration_steps=2)
    meta, params = trainer.get_parameters()
    m1, m2 = trainer.get_momenta()
    assert len(m1) == len(params) == len(m2)
    frozen_idx = [i for i, n in enumerate(meta.names) if "wte/embedding" in n]
    assert frozen_idx and all(np.all(m1[i] == 0) for i in frozen_idx)
    trainer.set_momenta(m1, m2)  # must not raise
    got_m1, _ = trainer.get_momenta()
    trainable = [i for i in range(len(params)) if i not in frozen_idx]
    np.testing.assert_allclose(got_m1[trainable[0]], m1[trainable[0]], rtol=1e-6)


@pytest.mark.slow
def test_fed_round_with_momenta_aggregation(tmp_path):
    from tests.test_federation import make_cfg, make_app

    cfg = make_cfg(tmp_path, n_rounds=2, aggregate_momenta=True)
    app = make_app(cfg, tmp_path)
    assert has_momenta(app.metadata)
    history = app.run()
    assert len(history.series("server/round_time")) == 2
    # aggregated momenta circulate: the extended payload is non-zero after training
    n = len(app.metadata.names) // 3
    momenta_norms = [float(np.linalg.norm(a)) for a in app.strategy.current_parameters[n:]]
    assert any(m > 0 for m in momenta_norms)
    app.driver.shutdown()
