"""Test harness: fake an 8-device TPU-like mesh on CPU.

SURVEY.md §4: the reference ships no tests; we build the pyramid ourselves.
Multi-chip behavior is tested on a virtual CPU device mesh
(``xla_force_host_platform_device_count``), per the driver's contract.
"""

import os

# force CPU: the env may preset JAX_PLATFORMS to the (single, tunneled) TPU
# chip, which tests must never contend for
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
