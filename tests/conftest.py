"""Test harness: fake an 8-device TPU-like mesh on CPU.

SURVEY.md §4: the reference ships no tests; we build the pyramid ourselves.
Multi-chip behavior is tested on a virtual 8-CPU-device mesh
(``jax.config jax_num_cpu_devices``), per the driver's contract.
"""

import jax

# Force CPU via jax.config (not env vars): the image's site hook pre-imports
# jax and registers the real (single, tunneled) TPU chip, so env vars set here
# are read too late. jax.config.update works any time before backend init.
jax.config.update("jax_platforms", "cpu")

from photon_tpu.utils.compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(8)
jax.config.update("jax_threefry_partitionable", True)

# Persistent compile cache: jit compiles dominate suite wall time (VERDICT
# r3 weak #7 measured >9 min); warm-cache runs cut most of it. The dir is
# gitignored — first run per environment pays once. A user-set
# JAX_COMPILATION_CACHE_DIR is honored everywhere (in-process, spawned
# children via env inheritance, and tests/_helpers.subprocess_env).
#
# ONLY on the jax.shard_map era, though: jax 0.4.37's cache can deserialize
# a donated-buffer executable with broken input-output aliasing — observed
# as a warm-cache train step that computes the correct loss but returns the
# donated input state UNCHANGED, silently failing any test that asserts
# parameter updates (tests/_helpers.CACHE_SAFE carries the same gate to
# subprocess children).
import os as _os  # noqa: E402

from tests._helpers import CACHE_SAFE as _CACHE_SAFE  # noqa: E402
from tests._helpers import TEST_JAX_CACHE as _TEST_JAX_CACHE  # noqa: E402

if _CACHE_SAFE:
    jax.config.update("jax_compilation_cache_dir", _TEST_JAX_CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _TEST_JAX_CACHE)
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Deselect `slow` tests by default, keeping two escape hatches: an
    explicit ``-m`` expression, or naming a test by node id
    (``pytest tests/test_federation.py::test_failure_budget`` must never
    report 'no tests ran' because of a hidden default filter)."""
    if config.option.markexpr:
        return  # user chose, e.g. -m "slow or not slow" (make test-all)
    if getattr(config.option, "keyword", ""):
        return  # -k filtered runs pick their own tests, incl. slow ones
    if any("::" in arg for arg in config.args):
        return  # explicit node ids run regardless of markers
    selected, deselected = [], []
    for item in items:
        (deselected if item.get_closest_marker("slow") else selected).append(item)
    if deselected:
        config.hook.pytest_deselected(items=deselected)
        items[:] = selected


def pytest_configure(config):
    # (the `slow` marker itself is registered in pytest.ini)
    # build the native helper lib so test_native.py exercises the C++ paths
    # in a plain `pytest tests/` run instead of silently skipping (VERDICT r2
    # weak #8); best-effort — the package degrades to numpy fallbacks
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).parent.parent
    so = root / "native" / "libphoton_native.so"
    src = root / "native" / "photon_native.cpp"
    if src.exists() and (
        not so.exists() or so.stat().st_mtime < src.stat().st_mtime
    ):
        try:
            subprocess.run(
                ["make", "native"], cwd=root, capture_output=True, timeout=120, check=False
            )
        except (OSError, subprocess.TimeoutExpired):
            pass  # no toolchain: numpy fallbacks keep the suite green


@pytest.fixture(scope="module")
def tiny_trainer():
    """A single-device Trainer on a tiny model + one synthetic batch."""
    from photon_tpu.config.schema import (
        Config, MeshConfig, ModelConfig, OptimizerConfig, SchedulerConfig, TrainConfig,
    )
    from photon_tpu.train.trainer import Trainer

    cfg = Config(
        model=ModelConfig(
            d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
            attn_impl="xla", compute_dtype="float32",
        ),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=50),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4),
    )
    trainer = Trainer(cfg, init_seed=0)
    batch = np.random.default_rng(0).integers(0, 64, (4, 16), dtype=np.int64)
    return trainer, batch
