"""Test harness: fake an 8-device TPU-like mesh on CPU.

SURVEY.md §4: the reference ships no tests; we build the pyramid ourselves.
Multi-chip behavior is tested on a virtual 8-CPU-device mesh
(``jax.config jax_num_cpu_devices``), per the driver's contract.
"""

import jax

# Force CPU via jax.config (not env vars): the image's site hook pre-imports
# jax and registers the real (single, tunneled) TPU chip, so env vars set here
# are read too late. jax.config.update works any time before backend init.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: multiprocess / long-compile tests")
    # build the native helper lib so test_native.py exercises the C++ paths
    # in a plain `pytest tests/` run instead of silently skipping (VERDICT r2
    # weak #8); best-effort — the package degrades to numpy fallbacks
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).parent.parent
    so = root / "native" / "libphoton_native.so"
    src = root / "native" / "photon_native.cpp"
    if src.exists() and (
        not so.exists() or so.stat().st_mtime < src.stat().st_mtime
    ):
        try:
            subprocess.run(
                ["make", "native"], cwd=root, capture_output=True, timeout=120, check=False
            )
        except (OSError, subprocess.TimeoutExpired):
            pass  # no toolchain: numpy fallbacks keep the suite green


def free_port() -> int:
    """Bind-port-0 trick for subprocess tests (TCP driver, jax.distributed)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def subprocess_env() -> dict:
    """Env for spawned children: repo APPENDED to PYTHONPATH (never replace —
    /root/.axon_site must stay importable), TPU plugin registration skipped
    (PALLAS_AXON_POOL_IPS="" — a second relay claimant wedges the chip), CPU
    backend forced."""
    import os
    import pathlib

    env = dict(os.environ)
    repo = str(pathlib.Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env


@pytest.fixture(scope="module")
def tiny_trainer():
    """A single-device Trainer on a tiny model + one synthetic batch."""
    from photon_tpu.config.schema import (
        Config, MeshConfig, ModelConfig, OptimizerConfig, SchedulerConfig, TrainConfig,
    )
    from photon_tpu.train.trainer import Trainer

    cfg = Config(
        model=ModelConfig(
            d_model=32, n_layers=2, n_heads=2, max_seq_len=16, vocab_size=64,
            attn_impl="xla", compute_dtype="float32",
        ),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(name="adopt", lr=1e-3),
        scheduler=SchedulerConfig(t_warmup=2, t_max=50),
        train=TrainConfig(global_batch_size=4, device_microbatch_size=4),
    )
    trainer = Trainer(cfg, init_seed=0)
    batch = np.random.default_rng(0).integers(0, 64, (4, 16), dtype=np.int64)
    return trainer, batch
