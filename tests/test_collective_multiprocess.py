"""Collective aggregation across REAL process boundaries (VERDICT r3 #6).

Spawns two ``jax.distributed`` CPU processes (2 local devices each → a
4-client global mesh) and runs :func:`collective_weighted_average` as a true
multi-controller SPMD program — the launch topology a multi-host TPU pod
uses, with the psum riding the distributed backend instead of
intra-process shared memory. Process 0 checks parity against the host
streaming-average oracle (``aggregate_inplace``)."""

import json
import subprocess
import sys

import numpy as np
import pytest

CHILD = r"""
import json, sys
import jax

pid = int(sys.argv[1]); port = sys.argv[2]; out_path = sys.argv[3]
jax.config.update("jax_platforms", "cpu")
from photon_tpu.utils.compat import set_cpu_device_count
set_cpu_device_count(2)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_tpu.parallel.collective_agg import (
    CLIENT_AXIS, collective_weighted_average, make_client_mesh,
)

N_CLIENTS = 4
assert len(jax.devices()) == N_CLIENTS, jax.devices()
mesh = make_client_mesh(N_CLIENTS)


def client_params(cid):
    rng = np.random.default_rng(cid)
    return {
        "w": rng.normal(size=(6, 4)).astype(np.float32),
        "b": rng.normal(size=(4,)).astype(np.float32),
    }

n_samples = np.asarray([10, 20, 5, 65], np.int32)
sharding = NamedSharding(mesh, P(CLIENT_AXIS))


def make_global(stacked_np):
    return jax.make_array_from_callback(
        stacked_np.shape, sharding, lambda idx: stacked_np[idx]
    )

stacked = {
    k: make_global(np.stack([client_params(c)[k] for c in range(N_CLIENTS)]))
    for k in ("w", "b")
}
ns = jax.make_array_from_callback(
    n_samples.shape, sharding, lambda idx: n_samples[idx]
)

avg = collective_weighted_average(stacked, ns, mesh)
# outputs are replicated -> fully addressable on every process
result = {k: np.asarray(v).tolist() for k, v in avg.items()}
with open(out_path, "w") as f:
    json.dump(result, f)
print(f"proc {pid} done", flush=True)
"""


@pytest.mark.slow
def test_collective_average_across_two_processes(tmp_path):
    from tests._helpers import free_port, subprocess_env

    port = free_port()
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    outs = [tmp_path / f"out_{pid}.json" for pid in range(2)]
    logs = [tmp_path / f"child_{pid}.log" for pid in range(2)]

    # child output goes to files, not PIPEs: proc 1's pipe is undrained
    # while proc 0 is being waited on — distributed-logging chatter past the
    # pipe buffer would deadlock the collective mid-psum
    procs = []
    for pid in range(2):
        with logs[pid].open("w") as logf:
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), str(pid), str(port), str(outs[pid])],
                    env=subprocess_env(), stdout=logf, stderr=subprocess.STDOUT,
                    text=True,
                )
            )
    for pid, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multiprocess collective aggregation timed out")
        assert p.returncode == 0, logs[pid].read_text()[-2000:]

    from photon_tpu.strategy.aggregation import aggregate_inplace

    def client_params(cid):
        rng = np.random.default_rng(cid)
        return [rng.normal(size=(6, 4)).astype(np.float32),
                rng.normal(size=(4,)).astype(np.float32)]

    n = [10, 20, 5, 65]
    oracle, total = aggregate_inplace(
        (client_params(c), n[c]) for c in range(4)
    )
    assert total == 100

    for out in outs:  # both controllers must hold identical averages
        got = json.loads(out.read_text())
        np.testing.assert_allclose(np.asarray(got["w"]), oracle[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got["b"]), oracle[1], rtol=1e-5, atol=1e-6)
