"""ClientRuntime eval over real converted shards must emit unigram-normalized
metrics (freq dicts written by the conversion pipeline)."""

import numpy as np

from photon_tpu.codec import params_to_ndarrays
from photon_tpu.data.convert import convert_corpus
from photon_tpu.data.tokenizer import ByteTokenizer
from photon_tpu.federation import ParamTransport
from photon_tpu.federation.client_runtime import ClientRuntime
from photon_tpu.federation.messages import EvaluateIns
from photon_tpu.models.mpt import init_params
from tests.test_federation import make_cfg


def test_eval_emits_unigram_metrics(tmp_path):
    tok = ByteTokenizer()
    docs = ["the quick brown fox jumps over the lazy dog " * 4] * 40
    for split in ("train", "val"):
        convert_corpus(docs, tmp_path / "data", tok, n_clients=2, seq_len=16, split=split)

    cfg = make_cfg(tmp_path, n_total_clients=2)
    cfg.model.vocab_size = 257 + 63  # cover tokenizer vocab, keep head-divisible
    cfg.dataset.synthetic = False
    cfg.dataset.local_path = str(tmp_path / "data")
    cfg.dataset.split_eval = "val"

    rt = ClientRuntime(cfg, ParamTransport("inline"))
    meta, arrays = params_to_ndarrays(init_params(cfg.model, seed=0))
    ptr = rt.transport.put("test", meta, arrays)
    res = rt.evaluate(EvaluateIns(server_round=1, cids=[0], params=ptr, max_batches=2), cid=0)
    assert res.error is None, res.error
    assert "eval/UnigramNormalizedLanguageCrossEntropy" in res.metrics
    np.testing.assert_allclose(
        res.metrics["eval/UnigramNormalizedLanguageCrossEntropy"],
        res.metrics["eval/loss"] - res.metrics["eval/PureUnigramCrossEntropy"],
        rtol=1e-6,
    )
    # a random-init model cannot beat the unigram floor of real text
    assert res.metrics["eval/UnigramNormalizedLanguageCrossEntropy"] > 0
