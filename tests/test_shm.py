"""shm plane tests: codec roundtrip, commit protocol, cross-process hand-off
(reference behavioral oracle: single-writer/single-reader + spin-wait,
``photon/shm/utils.py``)."""

import multiprocessing as mp
import os
import uuid

import numpy as np
import pytest

from photon_tpu.codec import ParamsMetadata
from photon_tpu.shm import (
    read_blob,
    read_params,
    read_scalar,
    unlink,
    wait_for,
    write_blob,
    write_params,
    write_scalar,
)
from photon_tpu.shm.plane import cleanup_stale, sweep_stale_tmp


@pytest.fixture
def name():
    n = f"test-{uuid.uuid4().hex[:8]}"
    yield n
    unlink(n)


def _arrays():
    rng = np.random.default_rng(0)
    return [
        rng.normal(size=(4, 8)).astype(np.float32),
        rng.integers(0, 100, (3,)).astype(np.int64),
        rng.normal(size=(2, 2, 2)).astype(np.float32),
    ]


def test_params_roundtrip(name):
    arrays = _arrays()
    meta = ParamsMetadata.from_ndarrays(["a", "b", "c"], arrays)
    write_params(name, meta, arrays)
    meta2, arrays2 = read_params(name)
    assert meta2 == meta
    for a, b in zip(arrays, arrays2):
        np.testing.assert_array_equal(a, b)


def test_zero_copy_views_stable_across_rewrite(name):
    """Rewrites swap the file atomically (rename): existing zero-copy views
    keep the OLD snapshot; fresh reads see the new one."""
    arrays = _arrays()
    meta = ParamsMetadata.from_ndarrays(["a", "b", "c"], arrays)
    write_params(name, meta, arrays)
    _, views = read_params(name, copy=False)
    mutated = [a * 2 for a in arrays]
    write_params(name, meta, mutated)
    np.testing.assert_array_equal(views[0], arrays[0])  # old mapping intact
    _, fresh = read_params(name, copy=True)
    np.testing.assert_array_equal(fresh[0], mutated[0])


def test_read_before_commit_raises(name):
    from photon_tpu.shm.plane import ShmSegment

    seg = ShmSegment(name, size=64, create=True)
    seg.close()
    with pytest.raises(BlockingIOError):
        read_params(name)


def test_wait_for_timeout():
    with pytest.raises(TimeoutError):
        wait_for(f"never-{uuid.uuid4().hex[:6]}", timeout=0.2, poll=0.05)


def test_blob_and_scalar(name):
    write_blob(name, {"cid": 3, "cfg": [1, 2, 3]})
    assert read_blob(name) == {"cid": 3, "cfg": [1, 2, 3]}
    write_scalar(name, 42.5)
    assert read_scalar(name) == 42.5


def _child(name: str, q) -> None:
    wait_for(name, timeout=20)
    meta, arrays = read_params(name, copy=True)
    q.put((meta.names, [float(a.sum()) for a in arrays]))


def test_cross_process_handoff(name):
    """Writer parent, spin-waiting reader child (the NodeManager↔Worker
    pattern, ``node_manager_app.py:516-539``)."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    child = ctx.Process(target=_child, args=(name, q))
    child.start()
    arrays = _arrays()
    meta = ParamsMetadata.from_ndarrays(["a", "b", "c"], arrays)
    write_params(name, meta, arrays)
    names, sums = q.get(timeout=30)
    child.join(timeout=10)
    assert names == ("a", "b", "c")
    np.testing.assert_allclose(sums, [float(a.sum()) for a in arrays], rtol=1e-6)


def test_cleanup_stale():
    n = f"stale-{uuid.uuid4().hex[:8]}"
    write_blob(n, 1)
    assert cleanup_stale("stale-") >= 1
    from photon_tpu.shm.plane import _path

    assert not _path(n).exists()


@pytest.mark.chaos
def test_sweep_stale_tmp_reaps_dead_writers_only():
    """A node SIGKILLed mid-write leaks a pid-suffixed temp segment; the
    transport-startup sweep reaps it iff the writer pid is dead — a live
    writer's in-flight temp file must survive."""
    import subprocess

    from photon_tpu.shm.plane import SHM_DIR

    proc = subprocess.Popen(["sleep", "0"])
    proc.wait()  # reaped: the pid is guaranteed dead (not just a zombie)
    tag = uuid.uuid4().hex[:8]
    orphan = SHM_DIR / f"photon-{tag}-params.tmp-{proc.pid}"
    own = SHM_DIR / f"photon-{tag}-own.tmp-{os.getpid()}"
    orphan.write_bytes(b"torn")
    own.write_bytes(b"inflight")
    try:
        assert sweep_stale_tmp() >= 1
        assert not orphan.exists()
        assert own.exists()  # our own pid is alive: left alone
    finally:
        orphan.unlink(missing_ok=True)
        own.unlink(missing_ok=True)


@pytest.mark.chaos
def test_transport_startup_sweeps_orphans():
    import subprocess

    from photon_tpu.federation.transport import ParamTransport
    from photon_tpu.shm.plane import SHM_DIR

    proc = subprocess.Popen(["sleep", "0"])
    proc.wait()
    orphan = SHM_DIR / f"photon-{uuid.uuid4().hex[:8]}.tmp-{proc.pid}"
    orphan.write_bytes(b"torn")
    try:
        t = ParamTransport("shm")
        t.cleanup()
        assert not orphan.exists()
    finally:
        orphan.unlink(missing_ok=True)


def test_large_params_threaded_copy(name):
    """>64MiB payload exercises the thread-pool copy path."""
    big = [np.arange(20_000_000, dtype=np.float32)]  # 80 MB
    meta = ParamsMetadata.from_ndarrays(["big"], big)
    write_params(name, meta, big)
    _, out = read_params(name, copy=False)
    np.testing.assert_array_equal(out[0][:5], big[0][:5])
    np.testing.assert_array_equal(out[0][-5:], big[0][-5:])
