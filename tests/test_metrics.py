"""Unigram-normalized metrics + history tests (reference oracles:
``photon/metrics/unigram_normalized_metrics.py`` semantics — normalized CE =
model CE − unigram CE; history mirrors rounds)."""

import numpy as np
import jax.numpy as jnp

from photon_tpu.metrics import (
    History,
    UnigramMetricAccumulator,
    model_cross_entropy,
    pure_unigram_cross_entropy,
    unigram_log_probs_from_counts,
    unigram_normalized_cross_entropy,
)


def test_pure_unigram_ce_uniform():
    """Uniform unigram distribution → CE = log(vocab)."""
    vocab = 16
    logp = np.full(vocab, -np.log(vocab), np.float32)
    targets = jnp.asarray(np.random.default_rng(0).integers(0, vocab, (4, 8)))
    ce = float(pure_unigram_cross_entropy(targets, jnp.asarray(logp)))
    np.testing.assert_allclose(ce, np.log(vocab), rtol=1e-6)


def test_normalized_ce_is_difference():
    rng = np.random.default_rng(1)
    vocab = 16
    logits = jnp.asarray(rng.normal(size=(2, 8, vocab)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, vocab, (2, 8)))
    logp = jnp.asarray(np.log(np.full(vocab, 1.0 / vocab, np.float32)))
    norm = float(unigram_normalized_cross_entropy(logits, targets, logp))
    ce = float(model_cross_entropy(logits, targets))
    uni = float(pure_unigram_cross_entropy(targets, logp))
    np.testing.assert_allclose(norm, ce - uni, rtol=1e-6)


def test_perfect_model_beats_unigram():
    """A model with all mass on the target must have negative normalized CE."""
    vocab = 8
    targets = np.asarray([[1, 2, 3]])
    logits = np.full((1, 3, vocab), -100.0, np.float32)
    for i, t in enumerate(targets[0]):
        logits[0, i, t] = 100.0
    logp = np.log(np.full(vocab, 1.0 / vocab, np.float32))
    norm = float(unigram_normalized_cross_entropy(jnp.asarray(logits), jnp.asarray(targets), jnp.asarray(logp)))
    assert norm < -1.0


def test_accumulator_token_weighted():
    from collections import Counter

    vocab = 8
    logp = unigram_log_probs_from_counts(Counter({i: 1 for i in range(vocab)}), vocab)
    acc = UnigramMetricAccumulator(unigram_log_probs=logp)
    rng = np.random.default_rng(2)
    for n in (4, 12):  # different batch sizes → weighting matters
        logits = rng.normal(size=(1, n, vocab)).astype(np.float32)
        targets = rng.integers(0, vocab, (1, n))
        acc.update(logits, targets)
    out = acc.compute()
    assert set(out) == {
        "LanguageCrossEntropy", "LanguagePerplexity", "PureUnigramCrossEntropy",
        "UnigramNormalizedLanguageCrossEntropy", "UnigramNormalizedPerplexity",
    }
    assert acc.n_tokens == 16
    np.testing.assert_allclose(
        out["UnigramNormalizedLanguageCrossEntropy"],
        out["LanguageCrossEntropy"] - out["PureUnigramCrossEntropy"],
        rtol=1e-6,
    )


def test_history_roundtrip():
    h = History()
    h.record(1, {"loss": 3.0, "acc": 0.1})
    h.record(2, {"loss": 2.5, "skipme": "not-a-float"})
    assert h.latest("loss") == 2.5
    assert h.series("loss") == [(1, 3.0), (2, 2.5)]
    assert "skipme" not in h.rounds
    h2 = History.from_dict(h.to_dict())
    assert h2.series("loss") == h.series("loss")
