"""Cross-process end-to-end federation over real TCP sockets + objstore
(VERDICT r3 #7): server CLI process + two node-agent processes + FileStore,
running fit + eval + checkpoint, then a separate resumed run.

This is the multi-node flow of the reference
(``scripts/fed_125m_example.sh:104-137``: superlink on one host, client-app
processes pointed at its address) driven through
``python -m photon_tpu.federated --tcp-listen`` and
``python -m photon_tpu.federation.tcp --connect``."""

import json
import pathlib
import subprocess
import sys

import pytest

from photon_tpu.config.schema import Config

from tests._helpers import free_port as _free_port
from tests._helpers import subprocess_env as _env


def _cfg(tmp_path) -> Config:
    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 2
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 64
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.train.global_batch_size = 4
    cfg.train.device_microbatch_size = 4
    cfg.train.eval_batches = 2
    cfg.fl.n_total_clients = 2
    cfg.fl.n_clients_per_round = 2
    cfg.fl.n_rounds = 2
    cfg.fl.local_steps = 2
    cfg.fl.eval_interval_rounds = 2
    cfg.dataset.synthetic = True
    cfg.photon.save_path = str(tmp_path / "run")
    cfg.photon.checkpoint = True
    # node agents load this YAML directly: the bulk plane must be declared
    # (the server CLI normalizes its own copy the same way)
    cfg.photon.comm_stack.objstore = True
    cfg.photon.comm_stack.shm = False
    cfg.run_uuid = "tcp-e2e"
    cfg.validate()
    return cfg


def _spawn_nodes(
    cfg_path: str, port: int, n: int, log_dir: pathlib.Path, run: str
) -> list[subprocess.Popen]:
    # node output goes to files, not PIPEs: nobody drains a PIPE until
    # wait(), so a chatty node would block on a full pipe buffer mid-round
    # and wedge the whole federation; per-run filenames keep run-1 logs
    # intact as diagnostics when the resume run fails
    procs = []
    for i in range(n):
        with (log_dir / f"{run}_node{i}.log").open("w") as out:
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "photon_tpu.federation.tcp",
                     "--connect", f"127.0.0.1:{port}",
                     "--node-id", f"node{i}", "--config", cfg_path],
                    env=_env(), stdout=out, stderr=subprocess.STDOUT, text=True,
                )
            )
    return procs


def _run_server(cfg_path: str, port: int, extra: list[str]) -> dict:
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.federated",
         "--config", cfg_path, "--tcp-listen", f"127.0.0.1:{port}",
         "--nodes", "2", *extra],
        env=_env(), capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(last)


@pytest.mark.slow
def test_tcp_two_process_fit_eval_checkpoint_resume(tmp_path):
    cfg = _cfg(tmp_path)
    cfg_path = str(tmp_path / "run.yaml")
    cfg.to_yaml(cfg_path)

    # --- run 1: 2 rounds of fit + eval, checkpoints to the FileStore -----
    port = _free_port()
    nodes = _spawn_nodes(cfg_path, port, 2, tmp_path, "run1")
    try:
        out = _run_server(cfg_path, port, extra=[])
        assert out["server/round_time"] > 0
        assert out["server/eval_loss"] > 0  # eval ran at round 2
    finally:
        for p in nodes:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    store_root = pathlib.Path(cfg.photon.save_path) / "store"
    rounds = sorted((store_root / "tcp-e2e" / "server").glob("*"))
    assert rounds, f"no server round checkpoints under {store_root}"

    # --- run 2: resume from the latest round over fresh processes --------
    port2 = _free_port()
    nodes2 = _spawn_nodes(cfg_path, port2, 2, tmp_path, "run2")
    try:
        out2 = _run_server(
            cfg_path, port2,
            extra=["--rounds", "3", "--set", "photon.resume_round=-1"],
        )
        assert out2["server/round_time"] > 0
    finally:
        for p in nodes2:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    # round 3 checkpoint exists after the resumed run
    rounds_after = sorted((store_root / "tcp-e2e" / "server").glob("*"))
    assert len(rounds_after) >= len(rounds)
    assert any(r.name == "3" for r in rounds_after), [r.name for r in rounds_after]
