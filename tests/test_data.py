"""Dataset pipeline tests: PTS format round-trip, loader determinism/resume,
conversion packing, unigram counts (SURVEY.md §4: we build the pyramid the
reference lacks)."""

import json
from collections import Counter

import numpy as np
import pytest

from photon_tpu.data import (
    LoaderState,
    ShardedDataset,
    ShardWriter,
    StreamingLoader,
    count_tokens,
    make_synthetic_dataset,
    merge_freq_dicts,
    probability_tensor,
)
from photon_tpu.data.convert import TokenPacker, convert_corpus
from photon_tpu.data.tokenizer import ByteTokenizer


def _write_range_dataset(path, n=100, seq=16, vocab=1000, per_shard=32):
    """Samples are [i, i, ...] so identity is visible from the value."""
    with ShardWriter(path, seq, vocab, per_shard) as w:
        for i in range(n):
            w.write(np.full(seq, i, np.int64))
    return ShardedDataset(path)


def test_shard_roundtrip(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=100, per_shard=32)
    assert len(ds) == 100
    assert len(ds.shard_sizes) == 4  # 32+32+32+4
    assert ds.shard_sizes[-1] == 4
    for i in [0, 31, 32, 99]:
        assert (ds[i] == i).all()
    assert ds.dtype == np.uint16


def test_shard_validation(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=10)
    ShardedDataset(tmp_path / "ds", validate=True)  # checksums ok
    with pytest.raises(IndexError):
        ds[10]
    with pytest.raises(ValueError):
        with ShardWriter(tmp_path / "bad", 8, vocab_size=4) as w:
            w.write(np.full(8, 99, np.int64))  # token >= vocab


def test_uint32_for_large_vocab(tmp_path):
    with ShardWriter(tmp_path / "big", 4, vocab_size=1 << 17) as w:
        w.write(np.full(4, 100_000, np.int64))
    ds = ShardedDataset(tmp_path / "big")
    assert ds.dtype == np.uint32
    assert (ds[0] == 100_000).all()


def test_loader_epoch_is_permutation(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=100)
    loader = StreamingLoader(ds, batch_size=10, seed=3, shuffle_block_size=16)
    seen = [int(b[j, 0]) for _ in range(10) for j, b in [(j, next(loader)) for j in range(10)]]
    # one epoch = each sample exactly once
    first_epoch = []
    loader2 = StreamingLoader(ds, batch_size=10, seed=3, shuffle_block_size=16)
    for _ in range(10):
        first_epoch.extend(int(v) for v in next(loader2)[:, 0])
    assert sorted(first_epoch) == list(range(100))
    assert first_epoch != list(range(100))  # actually shuffled
    del seen


def test_loader_determinism_and_resume(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=100)
    a = StreamingLoader(ds, batch_size=7, seed=5)
    ref = [next(a) for _ in range(30)]  # crosses epoch boundaries

    b = StreamingLoader(ds, batch_size=7, seed=5)
    for i in range(10):
        np.testing.assert_array_equal(next(b), ref[i])
    state = json.loads(json.dumps(b.state_dict()))  # serializable
    c = StreamingLoader(ds, batch_size=7, seed=5, state=LoaderState.from_dict(state))
    for i in range(10, 30):
        np.testing.assert_array_equal(next(c), ref[i])


def test_loader_epochs_differ(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=50)
    loader = StreamingLoader(ds, batch_size=50, seed=1, shuffle_block_size=8)
    e0, e1 = next(loader)[:, 0], next(loader)[:, 0]
    assert sorted(e0) == sorted(e1)
    assert list(e0) != list(e1)


def test_loader_skip_samples(tmp_path):
    ds = _write_range_dataset(tmp_path / "ds", n=40)
    a = StreamingLoader(ds, batch_size=4, seed=2)
    for _ in range(5):
        next(a)
    b = StreamingLoader(ds, batch_size=4, seed=2)
    b.skip_samples(20)
    np.testing.assert_array_equal(next(a), next(b))


def test_token_packer():
    p = TokenPacker(seq_len=5, eos_id=0)
    out = list(p.pack(np.array([1, 2, 3])))  # + eos -> 4 toks, no full row
    assert out == []
    out = list(p.pack(np.array([4, 5, 6])))  # tail 1,2,3,0 + 4,5,6,0 = 8 -> one row
    assert len(out) == 1
    np.testing.assert_array_equal(out[0], [1, 2, 3, 0, 4])
    # tail continues the stream exactly
    out2 = list(p.pack(np.array([7, 8])))
    np.testing.assert_array_equal(out2[0], [5, 6, 0, 7, 8])


def test_convert_corpus_partitions_and_freqs(tmp_path):
    tok = ByteTokenizer()
    docs = ["hello world", "abcdef" * 10, "xyz" * 30, "more text here"] * 6
    summary = convert_corpus(docs, tmp_path / "out", tok, n_clients=2, seq_len=8, split="train")
    assert summary["total_samples"] > 0
    sizes = []
    for i in range(2):
        ds = ShardedDataset(tmp_path / "out" / f"client_{i}" / "train")
        sizes.append(len(ds))
        freq_file = tmp_path / "out" / f"client_{i}" / "train" / "unigram_freq.json"
        assert freq_file.exists()
    assert abs(sizes[0] - sizes[1]) <= 1  # round-robin balance
    assert sum(sizes) == summary["total_samples"]


def test_unigram_probability_tensor(tmp_path):
    ds = make_synthetic_dataset(tmp_path / "syn", n_samples=8, seq_len=32, vocab_size=64)
    counts = count_tokens(ds)
    assert sum(counts.values()) == 8 * 32
    merged = merge_freq_dicts([counts, Counter({0: 5})])
    assert merged[0] == counts[0] + 5
    probs = probability_tensor(counts, 64)
    assert probs.shape == (64,)
    np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-6)


def test_synthetic_dataset_deterministic(tmp_path):
    a = make_synthetic_dataset(tmp_path / "a", n_samples=16, seq_len=16, vocab_size=100, seed=7)
    b = make_synthetic_dataset(tmp_path / "b", n_samples=16, seq_len=16, vocab_size=100, seed=7)
    for i in range(16):
        np.testing.assert_array_equal(a[i], b[i])
