"""Shared helpers for subprocess-spawning tests.

A plain module (NOT conftest) so test files can import it without
re-executing conftest's module-level jax.config setup under a second module
name (`tests.conftest` vs pytest's top-level `conftest`).
"""

from __future__ import annotations

import os
import pathlib
import socket

# honor a user-set cache dir; default to the suite's persistent cache
TEST_JAX_CACHE = os.environ.get("JAX_COMPILATION_CACHE_DIR") or str(
    pathlib.Path(__file__).parent / ".jax_cache"
)


def free_port() -> int:
    """Bind-port-0 trick for subprocess tests (TCP driver, jax.distributed)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def subprocess_env() -> dict:
    """Env for spawned children: repo APPENDED to PYTHONPATH (never replace —
    /root/.axon_site must stay importable), TPU plugin registration skipped
    (PALLAS_AXON_POOL_IPS="" — a second relay claimant wedges the chip), CPU
    backend forced, suite compile cache shared."""
    env = dict(os.environ)
    repo = str(pathlib.Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = TEST_JAX_CACHE
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    return env
