"""Shared helpers for subprocess-spawning tests.

A plain module (NOT conftest) so test files can import it without
re-executing conftest's module-level jax.config setup under a second module
name (`tests.conftest` vs pytest's top-level `conftest`).
"""

from __future__ import annotations

import os
import pathlib
import socket

# honor a user-set cache dir; default to the suite's persistent cache
TEST_JAX_CACHE = os.environ.get("JAX_COMPILATION_CACHE_DIR") or str(
    pathlib.Path(__file__).parent / ".jax_cache"
)


def _cache_safe() -> bool:
    """Persistent-cache gate (see conftest): jax releases without
    ``jax.shard_map`` (0.4.x) can deserialize donated-buffer executables
    with broken input-output aliasing — a warm cache silently turns train
    steps into no-ops there."""
    import jax

    return hasattr(jax, "shard_map")


CACHE_SAFE = _cache_safe()


def free_port() -> int:
    """Bind-port-0 trick for subprocess tests (TCP driver, jax.distributed)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def subprocess_env() -> dict:
    """Env for spawned children: repo APPENDED to PYTHONPATH (never replace —
    /root/.axon_site must stay importable), TPU plugin registration skipped
    (PALLAS_AXON_POOL_IPS="" — a second relay claimant wedges the chip), CPU
    backend forced, suite compile cache shared."""
    env = dict(os.environ)
    repo = str(pathlib.Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    if CACHE_SAFE:
        env["JAX_COMPILATION_CACHE_DIR"] = TEST_JAX_CACHE
        env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    return env


def tiny_llama_config(n_kv_heads: int = 0):
    """Shared tiny llama-family config for the checkpoint-interop tests
    (kept in one place so export/import tests can't drift apart)."""
    from photon_tpu.config.schema import Config

    cfg = Config()
    cfg.model.d_model = 32
    cfg.model.n_layers = 2
    cfg.model.n_heads = 4
    cfg.model.n_kv_heads = n_kv_heads
    cfg.model.max_seq_len = 16
    cfg.model.vocab_size = 96
    cfg.model.attn_impl = "xla"
    cfg.model.compute_dtype = "float32"
    cfg.model.logits_dtype = "float32"
    cfg.model.rope = True
    cfg.model.learned_pos_emb = False
    cfg.model.norm = "rmsnorm"
    cfg.model.mlp = "swiglu"
    cfg.model.mlp_hidden_size = 48
    cfg.model.tie_embeddings = False
    return cfg.validate()
