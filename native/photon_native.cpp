// photon-tpu native data-plane helpers.
//
// Role parity with the reference stack's native substrate (SURVEY.md §2.2):
// the reference leans on mosaicml-streaming's C++ shard handling and torch's
// C++ memcpy paths; photon-tpu's equivalents live here. Host-side only — all
// device math goes through XLA/Pallas.
//
//   pts_gather_widen : batch-gather PTS sample rows (uint16/uint32) into a
//                      contiguous int32 batch — the data-loader hot path.
//   par_memcpy       : multi-threaded memcpy — the shm-plane bulk-copy path
//                      (reference: threaded set_parameters_shm,
//                      photon/shm/utils.py:626-651).
//   crc32            : zlib-polynomial CRC (slice-by-1, table-based) for
//                      shard checksum validation without holding the GIL.
//
// Built with `make native` into libphoton_native.so; loaded via ctypes
// (pybind11 is not in the image). Every entry point is plain C ABI.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather n_rows rows into out[int32]. row_ptrs[i] points at row i's first
// token (uint16 when elem_size==2, uint32 when 4); each row has row_elems
// tokens. Fuses the gather with the int32 widen so the batch is written once.
void pts_gather_widen(const void** row_ptrs, int64_t n_rows, int64_t row_elems,
                      int elem_size, int32_t* out, int n_threads) {
  if (n_rows <= 0) return;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_rows) n_threads = (int)n_rows;

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int32_t* dst = out + i * row_elems;
      if (elem_size == 2) {
        const uint16_t* src = (const uint16_t*)row_ptrs[i];
        for (int64_t j = 0; j < row_elems; ++j) dst[j] = (int32_t)src[j];
      } else {
        const uint32_t* src = (const uint32_t*)row_ptrs[i];
        for (int64_t j = 0; j < row_elems; ++j) dst[j] = (int32_t)src[j];
      }
    }
  };

  if (n_threads == 1) {
    worker(0, n_rows);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = lo + chunk > n_rows ? n_rows : lo + chunk;
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& th : ts) th.join();
}

// Multi-threaded memcpy for large buffers (>= ~8 MiB pays off).
void par_memcpy(void* dst, const void* src, int64_t n, int n_threads) {
  if (n <= 0) return;
  const int64_t kMin = 8 << 20;
  if (n_threads < 1) n_threads = 1;
  int64_t max_threads = n / kMin;
  if (max_threads < 1) max_threads = 1;
  if (n_threads > max_threads) n_threads = (int)max_threads;
  if (n_threads == 1) {
    std::memcpy(dst, src, (size_t)n);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = (int64_t)t * chunk;
    int64_t hi = lo + chunk > n ? n : lo + chunk;
    if (lo >= hi) break;
    ts.emplace_back([=] {
      std::memcpy((char*)dst + lo, (const char*)src + lo, (size_t)(hi - lo));
    });
  }
  for (auto& th : ts) th.join();
}

// zlib-compatible CRC-32 (polynomial 0xEDB88320), table-based.
static uint32_t crc_table[256];
static std::atomic<bool> crc_init{false};

static void ensure_crc_table() {
  bool expected = false;
  static std::atomic<bool> building{false};
  if (crc_init.load(std::memory_order_acquire)) return;
  if (building.compare_exchange_strong(expected, true)) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
    crc_init.store(true, std::memory_order_release);
  } else {
    while (!crc_init.load(std::memory_order_acquire)) {}
  }
}

uint32_t crc32(uint32_t seed, const void* buf, int64_t n) {
  ensure_crc_table();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const uint8_t* p = (const uint8_t*)buf;
  for (int64_t i = 0; i < n; ++i) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // extern "C"
